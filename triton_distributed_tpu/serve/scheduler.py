"""The continuous-batching scheduler: overload-safe serving over the
paged KV cache.

Iteration-level scheduling (Orca, PAPERS.md) over PagedAttention-style
physical pages: every scheduler ``step()`` re-decides batch membership,
then dispatches ONE jit-compiled stateless decode step whose shapes
never change — membership edits only rewrite block-table / seq-lens /
token VALUES, so the hot path never retraces.  Robustness is the
headline; the mechanisms, in the order a step applies them:

1. **Admission control** (``RequestQueue`` + ``PagePool``): bounded
   queue depth sheds bursts at submit; admission reserves a prompt's
   pages against the explicit KV-page budget and stops (backpressure)
   when free pages dip under the headroom the degradation governor
   demands.
2. **Chunked prefill**: new sequences prefill ``prefill_chunk_tokens``
   prompt tokens per step alongside in-flight decode, so a long prompt
   cannot stall cohabitants' token cadence for its whole length.
3. **Preemption, not OOM**: a sequence growing into an exhausted pool
   (:class:`PagePoolExhausted` — the same typed error the cache-level
   bounds check raises) evicts the LOWEST-priority sequence: its pages
   return to the pool, the request parks back in the queue, and on
   re-admission it deterministically recomputes from its prompt
   (greedy/seeded sampling makes the replay exact).
4. **Per-request deadlines** ride the PR-3 watchdog machinery: the
   decode dispatch runs under ``resilience.call_with_deadline`` bounded
   by the tightest remaining request budget, and a breach fails ONLY
   the breached request(s).
5. **Per-sequence failure isolation** (PR 3's whole-batch isolation at
   sequence granularity): the step functions do NOT donate the cache,
   so a fault mid-step leaves the pre-step pools intact — the victim is
   failed, its pages reclaimed, its slot recycled, and cohabitants
   retry the step unharmed.
6. **Graceful degradation** (``resilience.AdmissionGovernor``): under
   preemption thrash or an open breaker the scheduler SHRINKS admission
   (fewer slots, more headroom) instead of failing requests.

Telemetry rides PR 5's plane: TTFT and request-latency sketches,
shed/preempt/evict counters and the pool-occupancy gauge land in
``obs.serve_stats``; ``health()`` reports ``status="saturated"`` under
sustained pool pressure, which ``obs.server`` turns into the
``/healthz`` 503 the load balancer sheds on.  Everything is
deterministic under a fixed seed — ``serve.trace`` replays an open-loop
arrival trace for the CI smoke (``scripts/tdt_lint.py --serve``) and
the fault matrix's scheduler cells (``resilience.matrix``).
"""

from __future__ import annotations

import dataclasses
import time

import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.kv_cache import PagePoolExhausted
from .budget import PagePool, lifecycle_recorder, page_event, pages_needed
from .queue import Request, RequestQueue, RequestState


@dataclasses.dataclass
class SchedulerConfig:
    """Knobs of the serving loop (defaults suit the CI-scale tests;
    production tunes via ``Engine.scheduler(**kw)``)."""

    max_queue_depth: int = 64
    # prompt tokens prefilled per scheduler step (None = whole prompt
    # in one chunk); also the EngineBackend's compile bucket
    prefill_chunk_tokens: int | None = None
    # extra free pages admission must leave (the governor ADDS to this
    # under degradation)
    admission_headroom_pages: int = 0
    # consecutive failed decode dispatches before the scheduler fails
    # every active request (a poisoned step that survives this many
    # victim evictions is not a single bad sequence)
    max_step_failures: int = 8
    # pool pressure must persist this long before health() flips to
    # "saturated" (503); 0 = immediately
    saturation_sustain_s: float = 0.0
    # lower bound on the bounded decode dispatch budget, so one request
    # with microseconds left cannot watchdog a healthy step
    step_deadline_floor_ms: float = 25.0
    # KV-pool audit cadence (steps) when TDT_INTEGRITY=1: full pages are
    # stamped (fold32) as they fill and re-verified every this-many
    # steps; a mismatch recovers the victim through the preemption-
    # recompute path.  Ignored (zero cost) with integrity off.
    kv_audit_interval_steps: int = 8
    # prefill-specialized tier (serve.router disaggregation): a request
    # completing prefill PARKS in HANDOFF state — pages held, first
    # token computed — instead of entering decode membership; the
    # router ships the pages to the decode tier (or colocates the
    # request back here when that tier is saturated)
    prefill_only: bool = False


@dataclasses.dataclass
class SlotState:
    """One active batch slot: the request plus its page map."""

    request: Request
    pages: list[int]
    length: int = 0          # valid KV positions (host truth)
    prefill_pos: int = 0     # prompt tokens already written
    next_token: int | None = None
    # TDT_INTEGRITY=1 only: logical page index -> fold32 stamp, taken
    # when the page FILLED (its bytes never legally change afterwards)
    page_stamps: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class StepResult:
    """What one ``step()`` did (tests and the lint smoke assert on
    these)."""

    admitted: int = 0
    prefill_tokens: int = 0
    decoded: int = 0
    completed: int = 0
    failed: int = 0
    preempted: int = 0
    shed: int = 0
    queue_depth: int = 0
    free_pages: int = 0
    active: int = 0

    @property
    def idle(self) -> bool:
        return (self.active == 0 and self.queue_depth == 0
                and self.admitted == 0)


class Scheduler:
    """Continuous-batching loop over one backend (see module
    docstring).  Single-threaded by design: ``submit`` is thread-safe
    (the queue locks), everything else runs on the caller's loop."""

    def __init__(self, backend, config: SchedulerConfig | None = None, *,
                 governor=None):
        from .. import resilience

        from .budget import scrub_enabled

        self.backend = backend
        self.cfg = config or SchedulerConfig()
        self.queue = RequestQueue(self.cfg.max_queue_depth)
        self.pool = PagePool(
            backend.pool_pages, backend.page_size,
            scrubber=self._scrub_pages if scrub_enabled() else None)
        # page-lifecycle attribution (analysis.pages): the recorder
        # names this pool's ops after our trace_tier
        self.pool.owner = self
        self.cache = backend.make_cache()
        self.slots: list[SlotState | None] = [None] * backend.slots
        self.governor = governor if governor is not None \
            else resilience.AdmissionGovernor()
        self.steps = 0
        self.admitted = 0
        self.completed: list[Request] = []
        self.failed: list[Request] = []
        self.shed: list[Request] = []
        self.preemptions = 0
        self.evicted_pages = 0
        self.decode_windows = 0
        self._consec_step_failures = 0
        self._saturated_since: float | None = None
        # TDT_INTEGRITY=1 KV-pool audit findings (req_id, page, step)
        self.kv_corruptions: list[dict] = []
        # request-trace tier tag (TDT_TRACE=1, obs.request_trace): the
        # router renames its tiers "prefill"/"decode" so cross-tier
        # span chains name where each hop ran
        self.trace_tier = "serve"
        # telemetry sink: defaults to the process-global sketches; the
        # fleet observability plane (obs.fleet_stats, TDT_FLEET_OBS=1)
        # swaps in a per-replica ``ReplicaStats`` that TEES every
        # observation into the global union, so per-replica drill-down
        # costs nothing when federation is off
        self.stats = obs.serve_stats.STATS

    # -- submission --------------------------------------------------------

    def submit(self, req: Request, *, now: float | None = None) -> bool:
        """Admission control stage 1: reject-or-queue.  A request whose
        TOTAL demand can never fit the pool (or ``max_length``) is shed
        immediately with a typed reason — queueing it would waste its
        deadline on an impossible promise."""
        now = time.monotonic() if now is None else now
        # mint (or, for a re-prefill resubmission, resume) the request
        # trace BEFORE the shed checks so a shed-at-submit is a traced
        # terminal outcome too; None whenever TDT_TRACE is off or this
        # thread is suppressed — every later hop then no-ops
        obs.request_trace.maybe_begin(req, self.trace_tier)
        # eager deadline sweep (ISSUE 7 satellite): expired entries must
        # not occupy depth against THIS submit — between ticks a burst
        # would otherwise shed viable work because the queue is "full"
        # of requests that can never run, and the depth gauge / the
        # saturation 503 would count them
        for dead in self.queue.expire_deadlines(now):
            self._note_shed(dead)
        total = req.prompt_len + req.max_new_tokens
        reason = None
        if total > self.backend.max_length:
            reason = (f"prompt {req.prompt_len} + max_new_tokens "
                      f"{req.max_new_tokens} exceeds max_length "
                      f"{self.backend.max_length}")
        elif pages_needed(total, self.pool.page_size) > self.pool.capacity:
            reason = (f"demand of {pages_needed(total, self.pool.page_size)}"
                      f" pages exceeds the pool capacity "
                      f"{self.pool.capacity} — can never be scheduled")
        if reason is not None:
            req.state = RequestState.SHED
            req.shed_reason = reason
            req.finished_s = now
            self._note_shed(req)
            return False
        if not self.queue.submit(req, now=now):
            self._note_shed(req)
            return False
        return True

    # -- the scheduler step ------------------------------------------------

    def step(self) -> StepResult:
        """One scheduling iteration: expire -> admit -> prefill ->
        decode -> account.  The tick runs under a process-level
        ``step`` span (ISSUE 14 satellite) so the scheduler shares one
        Chrome timeline with the comm/compute spans and the per-request
        traces."""
        with obs.span("sched_step", "step", tier=self.trace_tier):
            return self._step_impl()

    def _step_impl(self) -> StepResult:
        now = time.monotonic()
        res = StepResult()
        self.steps += 1
        # terminal-outcome counting by DELTA over the lifetime lists:
        # every path that finishes/fails/sheds/preempts (decode faults,
        # prefill faults, max_new==1 finishing inside prefill, deadline
        # sweeps) lands in the step's result without per-path plumbing
        c0, f0, s0, p0 = (len(self.completed), len(self.failed),
                          len(self.shed), self.preemptions)

        for req in self.queue.expire_deadlines(now):
            self._note_shed(req)

        # active-request deadline breaches fail in isolation, no step
        # spent on them
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            rem = slot.request.remaining_ms(now)
            if rem is not None and rem <= 0:
                self._fail_slot(
                    i, f"deadline {slot.request.deadline_ms:.0f} ms "
                       f"exceeded mid-flight", now)

        res.admitted = self._admit(now)
        self.admitted += res.admitted
        res.prefill_tokens = self._prefill_work(now)
        res.decoded = self._decode_work(now)

        from ..resilience import integrity

        if self.cfg.kv_audit_interval_steps > 0 and integrity.enabled():
            self._kv_audit(now)
        res.completed = len(self.completed) - c0
        res.failed = len(self.failed) - f0
        res.shed = len(self.shed) - s0
        res.preempted = self.preemptions - p0

        # a step with no decode work and no failures is still a CLEAN
        # step for the governor: degradation must decay while the loop
        # idles, or a raised headroom could block the last queued
        # request forever (the decode path feeds note_step_ok itself)
        if res.decoded == 0 and res.failed == 0 and res.preempted == 0:
            self.governor.note_step_ok()
        res.queue_depth = self.queue.depth
        res.free_pages = self.pool.free_pages
        res.active = sum(s is not None for s in self.slots)
        self._publish_gauges()
        # continuous profiler step boundary (TDT_PROFILE=1, ISSUE 16):
        # drain the flight ring incrementally into this tier's rollups
        # and rotate the window when due; anomalous windows advise the
        # governor.  One cached-bool check when unarmed.
        obs.continuous.on_step(self.trace_tier, self.steps,
                               governor=self.governor)
        return res

    def run_until_idle(self, *, max_steps: int = 100_000) -> int:
        """Drive ``step()`` until no queued and no active work remains;
        returns the step count.  ``max_steps`` guards a livelock bug
        from hanging CI."""
        for _ in range(max_steps):
            if self.step().idle:
                return self.steps
        raise RuntimeError(
            f"scheduler not idle after {max_steps} steps: "
            f"{self.debug_state()}")

    # -- admission ---------------------------------------------------------

    def _admit(self, now: float) -> int:
        cap = self.governor.slot_cap(len(self.slots))
        headroom = (self.cfg.admission_headroom_pages
                    + self.governor.headroom_pages())
        admitted = 0
        blocked_by_pages = False
        while True:
            if sum(s is not None for s in self.slots) >= cap:
                break
            req = self.queue.peek()
            if req is None:
                break
            # reserve the prompt plus the first decode token's slot; the
            # rest grows page-at-a-time under the preemption policy
            need = pages_needed(req.prompt_len + 1, self.pool.page_size)
            if self.pool.free_pages - need < headroom:
                blocked_by_pages = True
                break
            pages = self.pool.try_alloc(need)
            if pages is None:
                blocked_by_pages = True
                break
            if not self.queue.pop_if(req):
                # a concurrent submit changed the head between the peek
                # and this commit: give the pages back and re-peek
                self.pool.free(pages)
                continue
            slot_idx = next(
                i for i, s in enumerate(self.slots) if s is None)
            req.state = RequestState.PREFILL
            self.slots[slot_idx] = SlotState(request=req, pages=pages)
            admitted += 1
            if req.trace is not None:
                req.trace.annotate("admitted", tier=self.trace_tier,
                                   slot=slot_idx, pages=len(pages))
            if obs.enabled():
                obs.counter("serve_admitted").inc()
        # saturation: pool pressure with a live backlog
        if blocked_by_pages and self.queue.depth > 0:
            if self._saturated_since is None:
                self._saturated_since = now
        else:
            self._saturated_since = None
        return admitted

    # -- prefill -----------------------------------------------------------

    def _prefill_work(self, now: float) -> int:
        """One chunk per PREFILL slot per step: long prompts interleave
        with in-flight decode instead of monopolizing the loop."""
        budget = self.cfg.prefill_chunk_tokens
        done_tokens = 0
        for i, slot in enumerate(self.slots):
            if slot is None or slot.request.state is not RequestState.PREFILL:
                continue
            req = slot.request
            plen = req.prompt_len
            take = plen - slot.prefill_pos if budget is None \
                else min(budget, plen - slot.prefill_pos)
            # never exceed the backend's compile bucket: with the
            # default whole-prompt budget an EngineBackend would
            # otherwise reject (and fail) every prompt longer than its
            # one chunk executable
            bucket = getattr(self.backend, "chunk_tokens", None)
            if bucket is not None:
                take = min(take, bucket)
            chunk = req.prompt[slot.prefill_pos:slot.prefill_pos + take]
            if req.trace is not None:
                # chunk index + true_len land as tags; a recompute
                # (preemption restore or re-prefill fallback) is marked
                # so the attributor can name the re-paid prefill work
                req.trace.begin(
                    "prefill_chunk", tier=self.trace_tier,
                    start=slot.prefill_pos, tokens=int(take),
                    true_len=plen,
                    recompute=bool(req.preemptions
                                   or req.kv_stamps is not None))
            try:
                self.cache, first = self.backend.prefill_chunk(
                    self.cache, np.asarray(slot.pages, np.int32), chunk,
                    slot.prefill_pos, plen)
            except Exception as e:
                # a prefill fault is single-sequence by construction
                self._fail_slot(i, f"prefill failed: "
                                   f"{type(e).__name__}: {e}", now)
                continue
            if take and lifecycle_recorder() is not None:
                # lifecycle: this chunk's KV landed in these pages
                ps = self.pool.page_size
                page_event(
                    "write",
                    [slot.pages[j]
                     for j in range(slot.prefill_pos // ps,
                                    (slot.prefill_pos + take - 1) // ps
                                    + 1)],
                    pool=self.pool)
            slot.prefill_pos += take
            done_tokens += take
            if slot.prefill_pos >= plen:
                if req.kv_stamps and self._verify_restore(i, slot) \
                        is not None:
                    continue
                slot.length = plen
                slot.next_token = int(first)
                req.tokens = [int(first)]
                if lifecycle_recorder() is not None:
                    # lifecycle: the prompt's pages are now complete
                    # readable content (parked for handoff or entering
                    # decode membership)
                    page_event(
                        "seal",
                        slot.pages[:pages_needed(plen,
                                                 self.pool.page_size)],
                        pool=self.pool)
                # a prefill-only tier parks the finished prompt for the
                # router's handoff instead of entering decode (a
                # one-token request is already complete — nothing to
                # ship); the first token exists either way, so TTFT is
                # observed here in both modes
                if self.cfg.prefill_only and req.max_new_tokens > 1:
                    req.state = RequestState.HANDOFF
                    if req.trace is not None:
                        req.trace.begin("handoff_wait",
                                        tier=self.trace_tier)
                else:
                    req.state = RequestState.DECODE
                    if req.trace is not None:
                        req.trace.begin("decode_wait",
                                        tier=self.trace_tier)
                # TTFT is a per-REQUEST SLO, observed once on the FIRST
                # admission; a preempted request's re-prefill must not
                # contribute a second sample (it would inflate the p99
                # exactly in the thrash regime the sketch characterizes)
                if req.first_token_s is None:
                    req.first_token_s = time.monotonic()
                    if req.trace is not None:
                        req.trace.mark_first_token()
                    ttft = req.ttft_ms()
                    if obs.enabled() and ttft is not None:
                        self.stats.observe_ttft(
                            ttft,
                            exemplar=None if req.trace is None
                            else req.trace.trace_id)
                if req.max_new_tokens == 1:
                    self._finish_slot(i)
        return done_tokens

    # -- decode ------------------------------------------------------------

    def _active_decode(self) -> list[int]:
        return [i for i, s in enumerate(self.slots)
                if s is not None
                and s.request.state is RequestState.DECODE]

    def _window_steps(self, active: list[int]) -> int:
        """The membership-STABLE decode window (ISSUE 13,
        docs/serving.md "steps_per_dispatch"): how many steps the next
        dispatch may run with membership edits applied only BETWEEN
        dispatches.  Bounded by the backend's ``steps_per_dispatch``
        knob, by the steps until any member finishes (membership would
        change), and by the steps until any member outgrows its mapped
        pages (growth/preemption run between windows, so a window can
        neither leak a page nor preempt mid-flight)."""
        w = getattr(self.backend, "steps_per_dispatch", 1)
        if w <= 1:
            return 1
        ps = self.pool.page_size
        for i in active:
            slot = self.slots[i]
            w = min(w,
                    slot.request.max_new_tokens - len(slot.request.tokens),
                    len(slot.pages) * ps - slot.length)
        return max(int(w), 1)

    def _decode_work(self, now: float) -> int:
        """One batched decode dispatch — a membership-stable window of
        ``_window_steps`` steps (1 without the knob); returns the number
        of (sequence, step) decodes (terminal outcomes are counted by
        the caller's deltas)."""
        self._grow_pages()
        active = self._active_decode()
        if not active:
            return 0
        self._sync_cache()
        tokens = np.zeros((len(self.slots),), np.int32)
        for i in active:
            tokens[i] = self.slots[i].next_token
        window = self._window_steps(active)
        for i in active:
            tr = self.slots[i].request.trace
            if tr is not None:
                # window size + membership cohort (the PR-12
                # _window_steps decision) tag every dispatch hop
                tr.begin("decode_window", tier=self.trace_tier,
                         window=window, cohort=len(active))

        from .. import resilience

        try:
            new_cache, toks = self._dispatch(tokens, active, now, window)
        except Exception as e:
            # fresh clock: the breach typically happened DURING the
            # dispatch, after the step-start timestamp.  The whole
            # window is discarded — the non-donated step left the
            # pre-window cache intact, so cohabitants retry and a
            # preempted victim re-queues cleanly from its prompt
            self._isolate_step_failure(e, active, time.monotonic())
            return 0
        self._consec_step_failures = 0
        self.governor.note_step_ok()
        # feed the step breaker (sticky-open = the governor's max
        # degradation + a non-"ok" health status): consecutive step
        # failures walk it open, any success resets the count
        resilience.breaker(self.governor.breaker_op).record_success()
        self.cache = new_cache

        if lifecycle_recorder() is not None:
            # lifecycle: the dispatch attended over every member's
            # written pages and appended ``window`` tokens to its tail
            ps = self.pool.page_size
            for i in active:
                slot = self.slots[i]
                page_event("read",
                           slot.pages[:pages_needed(slot.length, ps)],
                           pool=self.pool)
                page_event(
                    "write",
                    [slot.pages[j]
                     for j in range(slot.length // ps,
                                    (slot.length + window - 1) // ps
                                    + 1)],
                    pool=self.pool)
        for s in range(window):
            for i in active:
                slot = self.slots[i]
                req = slot.request
                slot.length += 1
                tok = int(toks[s][i])
                req.tokens.append(tok)
                slot.next_token = tok
        for i in active:
            req = self.slots[i].request
            if len(req.tokens) >= req.max_new_tokens:
                self._finish_slot(i)
        if obs.enabled():
            self.stats.tokens.add(float(len(active) * window))
            obs.counter("serve_decode_steps").inc(window)
            obs.counter("serve_decode_windows").inc()
        self.decode_windows += 1
        return len(active) * window

    def _grow_pages(self) -> int:
        """Allocate the next page for every sequence whose write
        position has outgrown its map — preempting the lowest-priority
        sequence under pool pressure instead of letting ``append_paged``
        raise mid-step."""
        preempted = 0
        for i in list(self._active_decode()):
            slot = self.slots[i]
            if slot is None or \
                    slot.request.state is not RequestState.DECODE:
                continue   # may have been preempted as a victim below
            if slot.length < len(slot.pages) * self.pool.page_size:
                continue
            while True:
                page = self.pool.try_alloc(1)
                if page is not None:
                    slot.pages.extend(page)
                    break
                victim = self._choose_victim()
                if victim is None:
                    # nobody left to evict: admission guarantees a lone
                    # request fits, so this is a bookkeeping bug — fail
                    # the grower with the typed error rather than loop
                    self._fail_slot(
                        i, str(PagePoolExhausted(
                            "no page and no victim", needed=1,
                            available=0)), time.monotonic())
                    break
                self._preempt_slot(victim)
                preempted += 1
                if victim == i:
                    break   # the grower evicted itself; it is parked
        return preempted

    def _choose_victim(self) -> int | None:
        """Preemption policy: lowest priority; tie broken toward the
        YOUNGEST admission (it has the least sunk prefill work to
        recompute)."""
        best = None
        best_key = None
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            key = (slot.request.priority, -slot.request.req_id)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _dispatch(self, tokens: np.ndarray, active: list[int],
                  now: float, window: int = 1):
        """The bounded decode dispatch: per-request deadlines ride the
        PR-3 watchdog (``resilience.call_with_deadline``), budget = the
        tightest remaining request deadline, floored so one nearly-dead
        request cannot watchdog a healthy step.  A ``window`` > 1 runs
        the backend's multi-step bundle (ONE host dispatch for the
        whole membership-stable window); the return is normalized to
        ``(cache, (window, slots) tokens)``."""
        from .. import resilience

        remaining = [
            self.slots[i].request.remaining_ms(now) for i in active
        ]
        remaining = [r for r in remaining if r is not None]
        if window > 1:
            def thunk():
                return self.backend.decode_multi(self.cache, tokens,
                                                 window)
        else:
            def thunk():
                cache, nxt = self.backend.decode(self.cache, tokens)
                return cache, np.asarray(nxt, np.int32)[None]
        if not remaining and not resilience.enabled():
            return thunk()
        budget = None
        if remaining:
            # the floor is per STEP: a window of W legitimately takes ~W
            # single-step times, so an unscaled floor would watchdog a
            # healthy multi-step dispatch whenever any request runs low
            # and then fail an innocent victim W tokens at a time
            budget = max(min(remaining),
                         self.cfg.step_deadline_floor_ms * window)
        return resilience.call_with_deadline(
            "serve_decode_step", thunk, budget)

    def _isolate_step_failure(self, err: Exception, active: list[int],
                              now: float) -> int:
        """Per-sequence failure isolation: the pre-step cache was never
        replaced (non-donated step), so cohabitants' pages are intact —
        fail only the victim(s) and let the next step retry the rest.
        Victims: every request whose deadline has expired (a
        ``CollectiveTimeoutError`` step); otherwise the lowest-priority
        active sequence (the fault's attribution is not per-row, so the
        eviction policy picks, exactly as preemption does — but here the
        request FAILS, because replaying it would replay the fault)."""
        from .. import resilience

        self._consec_step_failures += 1
        self.governor.note_step_failure()
        resilience.breaker(self.governor.breaker_op).record_failure()
        if obs.enabled():
            obs.counter("serve_step_failures",
                        kind=type(err).__name__).inc()
        victims: list[int] = []
        if isinstance(err, resilience.CollectiveTimeoutError):
            for i in active:
                rem = self.slots[i].request.remaining_ms(now)
                if rem is not None and rem <= 0:
                    victims.append(i)
        if not victims:
            lowest = min(
                active,
                key=lambda i: (self.slots[i].request.priority,
                               -self.slots[i].request.req_id))
            victims = [lowest]
        if self._consec_step_failures > self.cfg.max_step_failures:
            victims = list(active)   # poisoned step, not a bad sequence
        failed = 0
        for i in victims:
            self._fail_slot(i, f"{type(err).__name__}: {err}", now)
            failed += 1
        return failed

    # -- KV-pool audit (TDT_INTEGRITY=1) -----------------------------------

    def _kv_audit(self, now: float) -> None:
        """Checksum the paged-KV pool (docs/robustness.md "Data
        integrity"): a page is STAMPED (``integrity.fold_page``) the
        step it fills — its bytes never legally change afterwards — and
        every ``kv_audit_interval_steps`` every stamped page is
        re-folded.  A mismatch is at-rest corruption
        (``corrupt_kv_page``): the victim is recovered through the
        preemption-recompute path (pages evicted, request re-queued,
        prompt deterministically recomputed) instead of shipping tokens
        attended over poisoned KV; cohabitants' caches are untouched."""
        from ..resilience import integrity

        ps = self.pool.page_size
        audit = self.steps % self.cfg.kv_audit_interval_steps == 0
        # collect every page this pass needs folded — newly-full pages
        # to stamp plus (on audit ticks) every stamped page to
        # re-verify — and fold them in ONE batched device read
        to_stamp: list[tuple[SlotState, int]] = []
        pages: set[int] = set()
        for slot in self.slots:
            if slot is None:
                continue
            written = max(slot.length, slot.prefill_pos)
            for j in range(written // ps):
                if j not in slot.page_stamps:
                    to_stamp.append((slot, j))
                    pages.add(int(slot.pages[j]))
            if audit:
                pages.update(int(slot.pages[j])
                             for j in slot.page_stamps)
        folds = integrity.fold_pages(self.cache, pages)
        for slot, j in to_stamp:
            slot.page_stamps[j] = folds[int(slot.pages[j])]
        if lifecycle_recorder() is not None and pages:
            # lifecycle: newly-full pages acquired their golden stamp;
            # an audit tick re-reads every stamped page
            if to_stamp:
                page_event("stamp",
                           [int(s.pages[j]) for s, j in to_stamp],
                           pool=self.pool)
            if audit:
                page_event("read", sorted(pages), pool=self.pool,
                           audit=True)
        if not audit:
            return
        for i, slot in enumerate(self.slots):
            if slot is None or not slot.page_stamps:
                continue
            if obs.enabled():
                obs.counter("integrity_checks", op="kv_audit").inc()
            bad = next(
                (j for j, want in sorted(slot.page_stamps.items())
                 if folds[int(slot.pages[j])] != want),
                None)
            if bad is None:
                continue
            page = int(slot.pages[bad])
            self.kv_corruptions.append({
                "req_id": slot.request.req_id, "page": page,
                "logical": int(bad), "step": self.steps,
            })
            if obs.enabled():
                obs.counter("integrity_failures", op="kv_audit",
                            kind="kv_page").inc()
            self._preempt_slot(i)

    def _verify_restore(self, i: int, slot: SlotState) -> int | None:
        """The verify-on-preempt-restore half of checksum-on-evict: the
        stamps carried through preemption pin the deterministic
        recompute.  A mismatch means the original write OR the recompute
        is corrupt — neither copy can be trusted, so the victim FAILS
        with the corruption named rather than shipping silently-
        divergent tokens.  Returns the bad logical page, or None."""
        from ..resilience import integrity

        req = slot.request
        folds = integrity.fold_pages(
            self.cache, [slot.pages[j] for j in req.kv_stamps])
        for j, want in sorted(req.kv_stamps.items()):
            if folds[int(slot.pages[j])] != want:
                if obs.enabled():
                    obs.counter("integrity_failures", op="kv_restore",
                                kind="kv_page").inc()
                self._fail_slot(
                    i, f"PayloadCorruption: recomputed KV page "
                       f"{int(slot.pages[j])} (logical {j}) of request "
                       f"{req.req_id} does not match its pre-eviction "
                       f"stamp", time.monotonic())
                return j
        if lifecycle_recorder() is not None and req.kv_stamps:
            # lifecycle: the recompute matched the pre-eviction stamps
            page_event("verify",
                       [int(slot.pages[j]) for j in req.kv_stamps],
                       pool=self.pool)
        req.kv_stamps = None
        return None

    # -- disaggregated handoff (serve.router, docs/serving.md) -------------

    def handoff_ready(self) -> list[int]:
        """Slots parked in HANDOFF state (``prefill_only`` tiers): the
        prompt's KV is finished, the first token computed, the pages
        held until the router ships — or colocates — the request."""
        return [i for i, s in enumerate(self.slots)
                if s is not None
                and s.request.state is RequestState.HANDOFF]

    def colocate(self, i: int) -> None:
        """Decode-tier-saturation fallback: finish the handoff-parked
        request HERE — its pages and first token are already in this
        tier's pool, so flipping it into decode membership costs
        nothing (the router sheds back to colocated mode instead of
        queueing transfers against a saturated tier)."""
        slot = self.slots[i]
        assert slot is not None and \
            slot.request.state is RequestState.HANDOFF
        slot.request.state = RequestState.DECODE
        if lifecycle_recorder() is not None:
            # lifecycle: the pages come home (possibly from a
            # mid-transfer extract the adopt refused) to local decode
            page_event("retain", slot.pages, pool=self.pool)
        if slot.request.trace is not None:
            slot.request.trace.annotate("colocated", tier=self.trace_tier)
            slot.request.trace.begin("decode_wait", tier=self.trace_tier)
        if obs.enabled():
            obs.counter("handoff_colocated").inc()

    def release_handoff(self, i: int) -> Request:
        """Release a handoff-parked slot after the router took
        ownership of the request (already adopted into the decode
        tier's membership, or bound for its re-prefill queue): pages
        return to this tier's pool, the slot recycles.  The request's
        state belongs to its NEW owner by now, so only the slot is
        asserted."""
        slot = self.slots[i]
        assert slot is not None
        return self._release_slot(i).request

    def can_adopt(self, req: Request) -> bool:
        """Cheap saturation probe for :meth:`adopt_prefilled` — the
        router consults it BEFORE paying the wire, so a transfer the
        tier would refuse is shed to colocated mode without queueing
        bytes against a saturated pool.  A request whose TOTAL demand
        can never fit this tier's pool (the same never-fits check
        ``submit`` applies) is refused outright: adopting it would
        thrash the pool with preemption-recompute cycles forever."""
        total = pages_needed(req.prompt_len + req.max_new_tokens,
                             self.pool.page_size)
        if total > self.pool.capacity or \
                req.prompt_len + req.max_new_tokens > \
                self.backend.max_length:
            return False
        cap = self.governor.slot_cap(len(self.slots))
        if sum(s is not None for s in self.slots) >= cap:
            return False
        headroom = (self.cfg.admission_headroom_pages
                    + self.governor.headroom_pages())
        need = pages_needed(req.prompt_len + 1, self.pool.page_size)
        return self.pool.free_pages - need >= headroom

    def adopt_prefilled(self, req: Request, implant, *, length: int,
                        next_token: int) -> bool:
        """Enter a request whose prompt KV was produced on ANOTHER tier
        (the verified handoff payload): allocate pages for
        ``length + 1`` positions under the SAME admission policy
        ``_admit`` applies (governor slot cap, pool headroom), write
        the payload into them via ``implant(cache, pages) -> cache``,
        and place the request directly into decode membership.
        Returns False — with NO side effects — when this tier cannot
        take it now (slots at the cap, pages short of headroom, or a
        total demand that can never fit — :meth:`can_adopt`): the
        router's cue to shed back to colocated mode."""
        if not self.can_adopt(req):
            return False
        need = pages_needed(length + 1, self.pool.page_size)
        pages = self.pool.try_alloc(need)
        if pages is None:
            return False
        if req.trace is not None:
            req.trace.begin("adopt", tier=self.trace_tier,
                            length=int(length), pages=need)
        try:
            self.cache = implant(self.cache, pages)
        except Exception:
            self.pool.free(pages)
            raise
        if lifecycle_recorder() is not None:
            # lifecycle: the implanted prompt pages passed the plane's
            # stamp verification before this call — mark them verified
            # and readable (the +1 growth reservation page stays
            # reserved until decode writes into it)
            used = pages[:pages_needed(int(length), self.pool.page_size)]
            page_event("verify", used, pool=self.pool)
            page_event("seal", used, pool=self.pool)
        slot_idx = next(i for i, s in enumerate(self.slots) if s is None)
        req.state = RequestState.DECODE
        req.tokens = [int(next_token)]
        self.slots[slot_idx] = SlotState(
            request=req, pages=pages, length=int(length),
            prefill_pos=req.prompt_len, next_token=int(next_token))
        self.admitted += 1
        if req.trace is not None:
            req.trace.begin("decode_wait", tier=self.trace_tier)
        if obs.enabled():
            obs.counter("serve_adopted").inc()
        return True

    # -- TDT_SCRUB_PAGES (docs/robustness.md flag matrix) ------------------

    def _scrub_pages(self, pages: list[int]) -> None:
        """Poison-fill recycled pages so any stale read before rewrite
        — a handoff implant mapping a freed page, a block-table row
        pointing at a recycled id — trips on the pattern
        deterministically instead of reading the previous tenant's
        plausible bytes."""
        from .budget import poison_value

        val = poison_value(np.dtype(self.cache.k.dtype))
        self.cache = dataclasses.replace(
            self.cache,
            k=self.cache.k.at[:, pages].set(val),
            v=self.cache.v.at[:, pages].set(val),
        )

    # -- slot lifecycle ----------------------------------------------------

    def _release_slot(self, i: int) -> SlotState:
        slot = self.slots[i]
        assert slot is not None
        self.slots[i] = None
        if slot.pages:
            self.pool.free(slot.pages)
        return slot

    def _finish_slot(self, i: int) -> None:
        slot = self._release_slot(i)
        req = slot.request
        req.state = RequestState.DONE
        req.finished_s = time.monotonic()
        self.completed.append(req)
        obs.request_trace.finish(req)
        if obs.enabled():
            e2e_ms = (req.finished_s - (req.submitted_s or req.finished_s)) \
                * 1e3
            self.stats.request_completed(
                e2e_ms, tokens=len(req.tokens),
                exemplar=None if req.trace is None else req.trace.trace_id)
            obs.counter("serve_completed").inc()

    def _fail_slot(self, i: int, error: str, now: float) -> None:
        slot = self._release_slot(i)
        req = slot.request
        req.state = RequestState.FAILED
        req.error = error
        req.finished_s = now
        self.failed.append(req)
        obs.request_trace.finish(req)
        if obs.enabled():
            self.stats.request_failed()
            obs.counter("serve_failed").inc()

    def _preempt_slot(self, i: int) -> None:
        slot = self._release_slot(i)
        npages = len(slot.pages)
        if slot.request.trace is not None:
            # the span runs until the recompute's first prefill chunk:
            # requeue wait + the re-paid admission are one episode
            slot.request.trace.begin("preempted", tier=self.trace_tier,
                                     pages=npages)
        self.preemptions += 1
        self.evicted_pages += npages
        self.governor.note_preemption()
        if slot.page_stamps and slot.request.kv_stamps is None:
            # checksum-on-evict (TDT_INTEGRITY=1; stamps only exist when
            # the audit armed them): carry the full-prompt-page stamps so
            # the recompute can be verified against the original write.
            # Only when NO carry is pending: a re-preemption during a
            # restore prefill must not replace the original-write stamps
            # with stamps of the still-UNVERIFIED recompute — the carry
            # survives until _verify_restore consumes it, so every
            # restore compares against the original write
            full_prompt = slot.request.prompt_len // self.pool.page_size
            carry = {j: s for j, s in slot.page_stamps.items()
                     if j < full_prompt}
            slot.request.kv_stamps = carry or None
        self.queue.requeue_preempted(slot.request)
        if obs.enabled():
            self.stats.request_preempted(pages=npages)
            obs.counter("serve_preemptions").inc()
            obs.counter("serve_evicted_pages").inc(npages)

    def _note_shed(self, req: Request) -> None:
        self.shed.append(req)
        obs.request_trace.finish(req)
        if obs.enabled():
            self.stats.request_shed()
            obs.counter("serve_shed").inc()

    # -- device-state reconciliation ---------------------------------------

    def _sync_cache(self) -> None:
        """Write the host truth into the device cache before a decode
        dispatch.  Only DECODE slots expose their real page map; every
        other row (empty, mid-prefill) points at the scrap page with
        length 0, so the batched step's unavoidable per-row writes land
        in garbage nobody reads instead of corrupting a prefilling
        cohabitant."""
        mp = self.cache.max_pages
        table = np.zeros((len(self.slots), mp), np.int32)
        lens = np.zeros((len(self.slots),), np.int32)
        for i, slot in enumerate(self.slots):
            if slot is not None and \
                    slot.request.state is RequestState.DECODE:
                table[i, :len(slot.pages)] = slot.pages
                lens[i] = slot.length
        self.cache = dataclasses.replace(
            self.cache,
            block_table=jnp.asarray(table),
            seq_lens=jnp.asarray(lens),
        )

    # -- health / introspection --------------------------------------------

    def saturated_s(self, now: float | None = None) -> float:
        if self._saturated_since is None:
            return 0.0
        return (time.monotonic() if now is None else now) \
            - self._saturated_since

    def health(self) -> dict:
        """The ``/healthz`` payload: resilience breaker state + live
        serve stats + this scheduler's state; ``status`` leaves "ok"
        under sustained pool saturation so ``obs.server`` answers 503 —
        the load-balancer backoff contract — and flips back as the
        backlog drains."""
        from .. import resilience

        snap = resilience.health_snapshot()
        snap["serve_stats"] = obs.serve_stats.STATS.snapshot()
        snap["scheduler"] = self.debug_state()
        sat = self.saturated_s()
        if snap["status"] == "ok" and self._saturated_since is not None \
                and sat >= self.cfg.saturation_sustain_s:
            snap["status"] = "saturated"
        return snap

    def debug_state(self) -> dict:
        return {
            "steps": self.steps,
            "admitted": self.admitted,
            "completed": len(self.completed),
            "failed": len(self.failed),
            "shed": len(self.shed),
            "preemptions": self.preemptions,
            "evicted_pages": self.evicted_pages,
            "decode_windows": self.decode_windows,
            "kv_corruptions": len(self.kv_corruptions),
            "handoff_parked": len(self.handoff_ready()),
            "active_slots": sum(s is not None for s in self.slots),
            "slot_cap": self.governor.slot_cap(len(self.slots)),
            "governor": self.governor.snapshot(),
            "saturated_s": self.saturated_s(),
            "queue": self.queue.snapshot(),
            "pool": self.pool.snapshot(),
        }

    def _publish_gauges(self) -> None:
        if not obs.enabled():
            return
        stats = obs.serve_stats.STATS
        occ = self.pool.occupancy()
        # bare keys: ServeStats' prometheus rendering prefixes `serve_`.
        # Each serve metric lives in exactly ONE exposition (the stats
        # block) — a registry twin under the same rendered name would
        # duplicate the metric family in /metrics and Prometheus rejects
        # the whole scrape.  kv_pool_occupancy also lands in the
        # registry (renders unprefixed, beside kv_cache_seq_occupancy —
        # no collision with serve_kv_pool_occupancy).
        stats.set_gauge("kv_pool_occupancy", occ)
        stats.set_gauge("active_slots",
                        float(sum(s is not None for s in self.slots)))
        stats.set_gauge("sched_queue_depth", float(self.queue.depth))
        obs.gauge("kv_pool_occupancy").set(occ)
