"""The explicit KV-page budget: a real free-list over the physical pool.

``init_serving_cache`` sizes the physical page pool independently of
``slots * max_pages`` — the pool IS the serving memory budget
(PagedAttention's central trick, PAPERS.md: logical capacity can
overcommit physical pages because most requests never reach
``max_length``).  :class:`PagePool` owns which physical page ids are
free: admission reserves a prompt's pages up front, decode grows a
sequence one page at a time, completion / preemption / failure return
pages — and an allocation that cannot be satisfied raises the same
typed :class:`~..models.kv_cache.PagePoolExhausted` the cache-level
bounds check uses, which is the scheduler's cue to preempt rather than
OOM.

Deterministic: pages allocate lowest-id-first, so a seeded load test
replays to identical block tables.  Page 0 is RESERVED as the scrap
page (inactive batch slots scatter their garbage token there).
"""

from __future__ import annotations

import threading

from ..models.kv_cache import PagePoolExhausted

SCRAP_PAGE = 0


class PageLifecycleError(ValueError):
    """A page-lifetime protocol breach at the pool boundary — the
    DYNAMIC twin of what ``analysis.pages`` flags statically: the
    message carries the page id and the violating transition so a
    crash and a lint finding read the same vocabulary.

    Subclasses :class:`ValueError` so pre-existing callers catching
    the old untyped errors keep working.
    """

    def __init__(self, message: str, *, page: int | None = None,
                 transition: str | None = None):
        super().__init__(message)
        self.page = page
        self.transition = transition


# ---------------------------------------------------------------------------
# lifecycle record hook (analysis.pages): the checker arms a recorder
# here and every page-op call site in serve/ funnels through
# ``page_event`` — one module-global load when unarmed, so the serving
# hot path pays nothing until TDT_VERIFY_PAGES (or a test/lint) arms it

_LIFECYCLE_RECORDER = None


def set_lifecycle_recorder(rec):
    """Install (or, with None, disarm) the page-lifecycle recorder;
    returns the previous recorder so callers can restore it."""
    global _LIFECYCLE_RECORDER
    prev = _LIFECYCLE_RECORDER
    _LIFECYCLE_RECORDER = rec
    return prev


def lifecycle_recorder():
    return _LIFECYCLE_RECORDER


def page_event(op: str, pages, *, pool=None, actor=None, **meta) -> None:
    """Emit one page operation into the armed recorder (no-op when
    unarmed).  ``pool`` keys the page ids (two tiers legitimately use
    the same physical ids); ``actor`` defaults to the owning
    scheduler's ``trace_tier``."""
    rec = _LIFECYCLE_RECORDER
    if rec is None:
        return
    rec.emit(op, pages, pool=pool, actor=actor, **meta)

# the TDT_SCRUB_PAGES poison values: distinctive constants (exact in
# every float dtype we pool) a stale read trips on DETERMINISTICALLY —
# a recycled page's previous-tenant bytes read plausibly (the PR-9
# stale-bytes hazard was patched only in the quantized write paths;
# this surfaces the whole class, handoff implants included)
POISON_FLOAT = -1024.0
POISON_INT8 = -109


def scrub_enabled() -> bool:
    """``TDT_SCRUB_PAGES=1``: poison-fill pages as they return to the
    free list (opt-in debugging aid; docs/robustness.md flag matrix)."""
    from ..core.utils import env_flag

    return env_flag("TDT_SCRUB_PAGES")


def poison_value(dtype) -> float:
    """The per-dtype poison pattern a recycled page is filled with."""
    import numpy as np

    return POISON_INT8 if np.dtype(dtype) == np.int8 else POISON_FLOAT


def pages_needed(num_tokens: int, page_size: int) -> int:
    """Pages to hold ``num_tokens`` KV positions."""
    if num_tokens < 0:
        raise ValueError(f"num_tokens {num_tokens} < 0")
    return -(-num_tokens // page_size)


class PagePool:
    """Free-list allocator over physical page ids [1, total_pages).

    ``alloc`` raises :class:`PagePoolExhausted`; ``try_alloc`` returns
    None — the scheduler uses the latter on its preemption path (an
    exception per probed allocation under sustained pressure would be
    noise).  Double-free and foreign-page frees raise a typed
    :class:`PageLifecycleError`: a bookkeeping bug here corrupts two
    sequences' caches silently, which is the one failure mode a
    robustness PR must never paper over.

    **Refcounted sharing** (the radix-prefix-cache substrate,
    ``analysis.pages`` certifies it): ``share`` takes an extra
    reference on live pages; ``free``/``release`` under refs is a
    RELEASE (the page stays allocated, nothing is scrubbed); the LAST
    release returns the page to the free list and only then may the
    TDT_SCRUB_PAGES scrubber poison-fill it — a shared page is never
    poison-filled under a live reader.
    """

    def __init__(self, total_pages: int, page_size: int, *,
                 scrubber=None):
        if total_pages < 2:
            raise ValueError(
                f"total_pages {total_pages} < 2 (page {SCRAP_PAGE} is "
                f"the reserved scrap page)")
        if page_size < 1:
            raise ValueError(f"page_size {page_size} < 1")
        self.total_pages = int(total_pages)
        self.page_size = int(page_size)
        # TDT_SCRUB_PAGES hook: called with the freed page ids AFTER the
        # free-list bookkeeping commits, from the owner's (single)
        # scheduling thread — the owner poison-fills the physical pages
        # so any stale read before rewrite trips deterministically
        self.scrubber = scrubber
        self._lock = threading.Lock()
        # lowest-id-first for deterministic replay
        self._free = list(range(1, total_pages))
        self._free_set = set(self._free)
        # page -> live reference count (absent = free); alloc starts a
        # page at 1, ``share`` increments, ``free``/``release``
        # decrement — the last release returns the page to the free
        # list and scrubs
        self._refs: dict[int, int] = {}
        # the owning Scheduler (if any) — the lifecycle recorder reads
        # its ``trace_tier`` to attribute this pool's ops to a tier
        self.owner = None

    @property
    def capacity(self) -> int:
        """Allocatable pages (scrap page excluded)."""
        return self.total_pages - 1

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - self.free_pages

    def occupancy(self) -> float:
        """Fraction of the allocatable pool in use (the serve gauge)."""
        return self.used_pages / self.capacity

    def try_alloc(self, n: int) -> list[int] | None:
        if n < 0:
            raise ValueError(f"alloc count {n} < 0")
        with self._lock:
            if n > len(self._free):
                return None
            pages, self._free = self._free[:n], self._free[n:]
            self._free_set.difference_update(pages)
            for p in pages:
                self._refs[p] = 1
        if pages and _LIFECYCLE_RECORDER is not None:
            page_event("alloc", pages, pool=self)
        return pages

    def alloc(self, n: int) -> list[int]:
        pages = self.try_alloc(n)
        if pages is None:
            raise PagePoolExhausted(
                f"page pool exhausted: need {n} page(s), "
                f"{self.free_pages} free of {self.capacity}",
                needed=n, available=self.free_pages,
            )
        return pages

    def share(self, pages) -> None:
        """Take an extra reference on live pages (the radix-prefix-
        cache primitive): each page's later ``free``/``release`` calls
        decrement, and only the LAST one returns it to the free list.
        Sharing a free page raises — a reference to recycled storage
        is exactly the stale-read hazard the scrub plane exists for."""
        pages = [int(p) for p in pages]
        with self._lock:
            for p in pages:
                if p not in self._refs:
                    raise PageLifecycleError(
                        f"share of free page {p} — taking a reference "
                        f"to recycled storage would read the next "
                        f"tenant's KV", page=p, transition="FREE->share")
            for p in pages:
                self._refs[p] += 1
        if _LIFECYCLE_RECORDER is not None:
            page_event("share", pages, pool=self)

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 = free)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    def free(self, pages) -> None:
        """Release one reference per page.  A page with references
        remaining stays allocated (a RELEASE — nothing is scrubbed);
        the last release returns it to the free list and only then is
        the scrubber allowed to poison-fill it, so a shared page is
        never poison-filled under a live reader."""
        pages = [int(p) for p in pages]
        final: list[int] = []
        released: list[int] = []
        with self._lock:
            for p in pages:
                if p == SCRAP_PAGE or not 0 < p < self.total_pages:
                    raise PageLifecycleError(
                        f"free of page {p} outside the allocatable pool "
                        f"[1, {self.total_pages})", page=p,
                        transition="free")
                if p in self._free_set or p not in self._refs:
                    raise PageLifecycleError(
                        f"double free of page {p} — two sequences would "
                        f"share it and corrupt each other's KV", page=p,
                        transition="FREE->free")
            for p in pages:
                self._refs[p] -= 1
                if self._refs[p] > 0:
                    released.append(p)
                    continue
                del self._refs[p]
                self._free_set.add(p)
                self._free.append(p)
                final.append(p)
            self._free.sort()
        if _LIFECYCLE_RECORDER is not None:
            if released:
                page_event("release", released, pool=self)
            if final:
                page_event("free", final, pool=self,
                           scrub_pending=self.scrubber is not None)
        # outside the lock: the scrubber touches device pools, and the
        # validation above has already committed the free.  Only the
        # FINAL releases scrub — the refcount IS the scrub refusal
        if final and self.scrubber is not None:
            self.scrubber(final)
            if _LIFECYCLE_RECORDER is not None:
                page_event("scrub", final, pool=self)

    # the refcount vocabulary the sharing callers (radix prefix cache)
    # read as acquire/share/release: ``alloc`` acquires fresh pages at
    # refcount 1, ``acquire``/``share`` take an extra reference, and
    # ``release``/``free`` drop one (last release scrubs)
    acquire = share
    release = free

    def snapshot(self) -> dict:
        with self._lock:
            free = len(self._free)
            shared = sum(r > 1 for r in self._refs.values())
        return {
            "capacity": self.capacity,
            "free_pages": free,
            "used_pages": self.capacity - free,
            "shared_pages": shared,
            "occupancy": (self.capacity - free) / self.capacity,
            "page_size": self.page_size,
        }
