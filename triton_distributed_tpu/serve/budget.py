"""The explicit KV-page budget: a real free-list over the physical pool.

``init_serving_cache`` sizes the physical page pool independently of
``slots * max_pages`` — the pool IS the serving memory budget
(PagedAttention's central trick, PAPERS.md: logical capacity can
overcommit physical pages because most requests never reach
``max_length``).  :class:`PagePool` owns which physical page ids are
free: admission reserves a prompt's pages up front, decode grows a
sequence one page at a time, completion / preemption / failure return
pages — and an allocation that cannot be satisfied raises the same
typed :class:`~..models.kv_cache.PagePoolExhausted` the cache-level
bounds check uses, which is the scheduler's cue to preempt rather than
OOM.

Deterministic: pages allocate lowest-id-first, so a seeded load test
replays to identical block tables.  Page 0 is RESERVED as the scrap
page (inactive batch slots scatter their garbage token there).
"""

from __future__ import annotations

import threading

from ..models.kv_cache import PagePoolExhausted

SCRAP_PAGE = 0

# the TDT_SCRUB_PAGES poison values: distinctive constants (exact in
# every float dtype we pool) a stale read trips on DETERMINISTICALLY —
# a recycled page's previous-tenant bytes read plausibly (the PR-9
# stale-bytes hazard was patched only in the quantized write paths;
# this surfaces the whole class, handoff implants included)
POISON_FLOAT = -1024.0
POISON_INT8 = -109


def scrub_enabled() -> bool:
    """``TDT_SCRUB_PAGES=1``: poison-fill pages as they return to the
    free list (opt-in debugging aid; docs/robustness.md flag matrix)."""
    from ..core.utils import env_flag

    return env_flag("TDT_SCRUB_PAGES")


def poison_value(dtype) -> float:
    """The per-dtype poison pattern a recycled page is filled with."""
    import numpy as np

    return POISON_INT8 if np.dtype(dtype) == np.int8 else POISON_FLOAT


def pages_needed(num_tokens: int, page_size: int) -> int:
    """Pages to hold ``num_tokens`` KV positions."""
    if num_tokens < 0:
        raise ValueError(f"num_tokens {num_tokens} < 0")
    return -(-num_tokens // page_size)


class PagePool:
    """Free-list allocator over physical page ids [1, total_pages).

    ``alloc`` raises :class:`PagePoolExhausted`; ``try_alloc`` returns
    None — the scheduler uses the latter on its preemption path (an
    exception per probed allocation under sustained pressure would be
    noise).  Double-free and foreign-page frees raise: a bookkeeping
    bug here corrupts two sequences' caches silently, which is the one
    failure mode a robustness PR must never paper over.
    """

    def __init__(self, total_pages: int, page_size: int, *,
                 scrubber=None):
        if total_pages < 2:
            raise ValueError(
                f"total_pages {total_pages} < 2 (page {SCRAP_PAGE} is "
                f"the reserved scrap page)")
        if page_size < 1:
            raise ValueError(f"page_size {page_size} < 1")
        self.total_pages = int(total_pages)
        self.page_size = int(page_size)
        # TDT_SCRUB_PAGES hook: called with the freed page ids AFTER the
        # free-list bookkeeping commits, from the owner's (single)
        # scheduling thread — the owner poison-fills the physical pages
        # so any stale read before rewrite trips deterministically
        self.scrubber = scrubber
        self._lock = threading.Lock()
        # lowest-id-first for deterministic replay
        self._free = list(range(1, total_pages))
        self._free_set = set(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable pages (scrap page excluded)."""
        return self.total_pages - 1

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.capacity - self.free_pages

    def occupancy(self) -> float:
        """Fraction of the allocatable pool in use (the serve gauge)."""
        return self.used_pages / self.capacity

    def try_alloc(self, n: int) -> list[int] | None:
        if n < 0:
            raise ValueError(f"alloc count {n} < 0")
        with self._lock:
            if n > len(self._free):
                return None
            pages, self._free = self._free[:n], self._free[n:]
            self._free_set.difference_update(pages)
            return pages

    def alloc(self, n: int) -> list[int]:
        pages = self.try_alloc(n)
        if pages is None:
            raise PagePoolExhausted(
                f"page pool exhausted: need {n} page(s), "
                f"{self.free_pages} free of {self.capacity}",
                needed=n, available=self.free_pages,
            )
        return pages

    def free(self, pages) -> None:
        pages = list(pages)
        with self._lock:
            for p in pages:
                p = int(p)
                if p == SCRAP_PAGE or not 0 < p < self.total_pages:
                    raise ValueError(
                        f"free of page {p} outside the allocatable pool "
                        f"[1, {self.total_pages})")
                if p in self._free_set:
                    raise ValueError(
                        f"double free of page {p} — two sequences would "
                        f"share it and corrupt each other's KV")
                self._free_set.add(p)
                self._free.append(p)
            self._free.sort()
        # outside the lock: the scrubber touches device pools, and the
        # validation above has already committed the free
        if self.scrubber is not None:
            self.scrubber([int(p) for p in pages])

    def snapshot(self) -> dict:
        with self._lock:
            free = len(self._free)
        return {
            "capacity": self.capacity,
            "free_pages": free,
            "used_pages": self.capacity - free,
            "occupancy": (self.capacity - free) / self.capacity,
            "page_size": self.page_size,
        }
