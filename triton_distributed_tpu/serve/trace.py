"""Seeded open-loop arrival traces and their replay harness.

Open-loop means arrivals do NOT wait for completions — the generator
schedules request arrivals against the scheduler's STEP COUNT (the
deterministic clock every box shares), so a trace that admits 2x the
KV-page budget reproduces the same admissions, preemptions and sheds on
every replay with the same seed.  Consumers: the CI smoke
(``scripts/tdt_lint.py --serve``), the fault matrix's scheduler cells
(``resilience.matrix``), the load tests (``tests/test_serve.py``), and
``bench.py serve`` (which adds wall-clock TTFT measurement on top).
"""

from __future__ import annotations

import dataclasses
import random

from .queue import Request, RequestState


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One trace entry: submit ``request`` when ``scheduler.steps``
    reaches ``step``."""

    step: int
    request: Request


def synthetic_trace(seed: int, n_requests: int, *,
                    mean_interarrival_steps: float = 1.0,
                    prompt_len: tuple[int, int] = (2, 12),
                    max_new: tuple[int, int] = (2, 10),
                    priorities: tuple[int, ...] = (0, 0, 0, 1, 2),
                    vocab: int = 101,
                    deadline_ms: float | None = None) -> list[Arrival]:
    """A seeded open-loop trace: geometric interarrival steps, uniform
    prompt/generation lengths, a priority mix skewed toward best-effort
    (the realistic shape: most traffic default priority, a few premium
    requests that must survive preemption)."""
    rng = random.Random(seed)
    arrivals = []
    step = 0
    for _ in range(n_requests):
        plen = rng.randint(*prompt_len)
        req = Request(
            prompt=tuple(rng.randrange(vocab) for _ in range(plen)),
            max_new_tokens=rng.randint(*max_new),
            priority=rng.choice(priorities),
            deadline_ms=deadline_ms,
        )
        arrivals.append(Arrival(step=step, request=req))
        if mean_interarrival_steps > 0:
            # geometric gap with the configured mean (0 gaps allowed:
            # bursts are the point of an open-loop overload trace)
            p = 1.0 / (1.0 + mean_interarrival_steps)
            gap = 0
            while rng.random() > p:
                gap += 1
            step += gap
    return arrivals


@dataclasses.dataclass
class TraceReport:
    """Replay outcome, with the two invariants the overload-safety
    acceptance rides on precomputed: ``leaked_pages`` (pool occupancy
    must return to zero once everything drains) and
    ``drain_monotone`` (after the last arrival, the OUTSTANDING request
    count — queued plus active, i.e. everything non-terminal — never
    grows: the backlog drains, it does not oscillate.  Raw queue depth
    is deliberately NOT the measure: a preemption legitimately moves a
    request active -> queued without creating work)."""

    requests: list[Request]
    steps: int
    leaked_pages: int
    drain_monotone: bool
    max_queue_depth: int
    peak_pool_occupancy: float

    @property
    def completed(self) -> list[Request]:
        return [r for r in self.requests
                if r.state is RequestState.DONE]

    @property
    def failed(self) -> list[Request]:
        return [r for r in self.requests
                if r.state is RequestState.FAILED]

    @property
    def shed(self) -> list[Request]:
        return [r for r in self.requests
                if r.state is RequestState.SHED]

    @property
    def ttft_ms(self) -> list[float]:
        return sorted(r.ttft_ms() for r in self.completed
                      if r.ttft_ms() is not None)

    def problems(self) -> list[str]:
        """The invariant violations a CI gate fails on."""
        out = []
        if self.leaked_pages:
            out.append(f"{self.leaked_pages} page(s) leaked after drain "
                       f"— a free-list bookkeeping bug")
        if not self.drain_monotone:
            out.append("queue depth grew after the last arrival — the "
                       "drain is not monotone")
        pending = [r for r in self.requests if not r.done]
        if pending:
            out.append(f"{len(pending)} request(s) never reached a "
                       f"terminal state: "
                       f"{[r.req_id for r in pending]}")
        return out


def replay(scheduler, arrivals: list[Arrival], *,
           max_steps: int = 100_000) -> TraceReport:
    """Drive the scheduler through the trace until every request is
    terminal (or ``max_steps`` fires — reported, not raised: a stuck
    replay is a finding for the caller's gate, not a crash).

    With ``TDT_VERIFY_PAGES=1`` the whole replay runs under the
    ``analysis.pages`` lifecycle recorder and raises
    ``ProtocolViolationError`` on any page-lifetime violation (leak,
    use-after-free, double-free, scrub-under-reader, ...); unset, the
    cost is one env check."""
    from ..core.utils import env_flag

    if not env_flag("TDT_VERIFY_PAGES"):
        return _replay_impl(scheduler, arrivals, max_steps=max_steps)
    from ..analysis.pages import maybe_record

    with maybe_record(label="serve_replay"):
        return _replay_impl(scheduler, arrivals, max_steps=max_steps)


def _replay_impl(scheduler, arrivals: list[Arrival], *,
                 max_steps: int = 100_000) -> TraceReport:
    pending = sorted(arrivals, key=lambda a: (a.step, a.request.req_id))
    requests = [a.request for a in pending]
    idx = 0
    last_arrival_step = pending[-1].step if pending else 0
    max_depth = 0
    peak_occ = 0.0
    prev_outstanding = None
    monotone = True
    for _ in range(max_steps):
        while idx < len(pending) and pending[idx].step <= scheduler.steps:
            scheduler.submit(pending[idx].request)
            idx += 1
        res = scheduler.step()
        max_depth = max(max_depth, res.queue_depth)
        peak_occ = max(peak_occ, scheduler.pool.occupancy())
        if idx >= len(pending) and scheduler.steps > last_arrival_step:
            outstanding = sum(not r.done for r in requests)
            if prev_outstanding is not None \
                    and outstanding > prev_outstanding:
                monotone = False
            prev_outstanding = outstanding
        if idx >= len(pending) and res.idle:
            break
    return TraceReport(
        requests=requests,
        steps=scheduler.steps,
        leaked_pages=scheduler.pool.used_pages,
        drain_monotone=monotone,
        max_queue_depth=max_depth,
        peak_pool_occupancy=peak_occ,
    )
