"""The fleet tier: N-replica serving with replica-loss failover,
per-replica quarantine, and SLO-driven rebalance (ROADMAP item 4).

:class:`DisaggRouter` scaled serving across PHASES — one prefill tier,
one decode tier.  :class:`FleetRouter` scales it across REPLICAS: N
:class:`~.scheduler.Scheduler` pools, each playing a prefill or decode
ROLE, sharing one handoff plane.  Three loops close here:

**Admission routing** is telemetry-driven over the same live gauges
``/metrics`` publishes: a request lands on the least-loaded ADMITTING
replica of its role (queue-depth fraction + pool occupancy), with
session AFFINITY — a session that already decoded on replica ``d1``
keeps landing on ``d1``, where its KV pages live — overridden only when
that replica is pressured or quarantined.

**The robustness core** is membership that survives faults:

- ``lose_replica``: a replica dying mid-decode re-prefills every
  resident request on a survivor through the existing
  retry→fallback→re-prefill ladder — pages reclaimed first (the
  page-lifecycle recorder sees every free), audit stamps carried on
  ``Request.kv_stamps`` exactly like a preemption (``_preempt_slot``'s
  carry rule), ORIGINAL submit clock and trace chain preserved so the
  lost replica's time stays on the request's latency sample.
- A FLAPPING replica (repeated step failures) walks its per-replica
  sticky breaker (``replica:<id>`` — the per-peer quarantine shape of
  ``resilience.integrity``) open: the replica DRAINS first (refuses
  admission, finishes residents), then evicts from membership, then
  re-earns admission through suppressed PROBE requests
  (``readmit_probe_successes`` consecutive green probes reset the
  breaker).  ``resilience.health_snapshot()`` reports the quarantine
  set as ``quarantined_replicas``.

**Rebalance** closes the measurement→actuation loop: the PR-13
attributor's ``dominant_phase`` over the live p99 sketch EXEMPLARS
(``request_ms`` for decode dominance, ``ttft_ms`` for prefill/queue
dominance), cross-checked against role-wide pressure, recruits a
replica from the other role — drain-before-convert, the donor role
never empties — and ``fleet_rebalance_convergence_steps`` (bench-gated)
counts detection→conversion.

Fault coverage lands in ``resilience.matrix`` as :data:`FleetFault`
cells (golden-pinned both directions by ``analysis.completeness``);
``scripts/tdt_lint.py --fleet`` replays the seeded N=4 fleet with an
abort and a flap injected and gates token parity + exact quarantine +
zero leaked pages per replica.
"""

from __future__ import annotations

import dataclasses
import enum
import time

from .. import obs
from ..obs import decisions
from ..obs import fleet_stats as fleet_obs
from . import handoff as handoff_mod
from .budget import pages_needed
from .queue import Request, RequestState
from .scheduler import Scheduler, StepResult

# the per-replica sticky breaker namespace — the same shape as the
# integrity plane's per-peer "peer:<rank>" quarantine: an open
# "replica:<id>" breaker IS the quarantine membership bit, and
# resilience.health_snapshot() aggregates the open set as
# ``quarantined_replicas``
REPLICA_BREAKER_PREFIX = "replica:"


def replica_breaker_name(replica_id: str) -> str:
    return REPLICA_BREAKER_PREFIX + str(replica_id)


class FleetFault(enum.Enum):
    """The fleet fault classes the matrix must cover (golden-pinned in
    ``resilience.matrix.FLEET_GOLDEN``; ``analysis.completeness``
    asserts the two stay in lockstep both directions)."""

    REPLICA_ABORT_MID_DECODE = "replica_abort_mid_decode"
    REPLICA_FLAP = "replica_flap"
    REBALANCE_UNDER_LOAD = "rebalance_under_load"
    QUARANTINE_READMIT = "quarantine_readmit"


FLEET_FAULT_KINDS = tuple(f.value for f in FleetFault)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet knobs.  The routing thresholds (``queue_pressure``,
    ``pool_pressure``) and pump knobs mirror :class:`RouterConfig`;
    the rest parameterize the quarantine walk and the rebalance loop."""

    max_transfers_per_step: int = 4
    queue_pressure: float = 0.75
    pool_pressure: float = 0.95
    colocate_on_saturation: bool = True
    adopt_patience_steps: int = 2
    bulk_bytes_per_step: int = 0
    step_wall_ms: float = 1.0
    # consecutive step failures before a replica's sticky breaker opens
    # (drain begins); the same threshold re-arms it during probation
    flap_threshold: int = 3
    # fleet steps between readmission probes of a quarantined replica
    probe_interval_steps: int = 16
    # consecutive green probes that re-earn admission
    readmit_probe_successes: int = 2
    # scheduler steps one probe request may take before it counts failed
    probe_max_steps: int = 64
    # failover ladder depth per request: a request that keeps failing on
    # SURVIVORS is the request's fault, not the fleet's — replaying it
    # forever would replay the fault forever
    max_failovers_per_request: int = 2
    # fleet steps between rebalance evaluations of the p99 exemplars
    rebalance_interval_steps: int = 16
    # consecutive dominant-phase evaluations before a recruit begins
    # (one anomalous window must not flip membership)
    rebalance_sustain: int = 2
    rebalance_enabled: bool = True


@dataclasses.dataclass
class Replica:
    """One fleet member: a scheduler pool plus its membership bits.
    ``draining``: refuses admission, finishes residents.  ``evicted``:
    out of membership (quarantined — probes may readmit it).  ``lost``:
    gone for good (crash/partition); never probed, never readmitted.
    ``recruiting``: draining toward a ROLE conversion, not an
    eviction."""

    replica_id: str
    scheduler: Scheduler
    role: str                     # "prefill" | "decode"
    draining: bool = False
    evicted: bool = False
    lost: bool = False
    recruiting: bool = False
    probe_successes: int = 0
    # high-water mark into scheduler.failed the flap watcher has seen
    _seen_failed: int = 0

    @property
    def quarantined(self) -> bool:
        return self.evicted and not self.lost


@dataclasses.dataclass
class FleetStepResult:
    """What one fleet ``step()`` did, per stepped replica plus the
    fleet-level deltas."""

    results: dict[str, StepResult]
    handoffs: int = 0
    colocated: int = 0
    reprefills: int = 0
    failovers: int = 0

    @property
    def idle(self) -> bool:
        return all(r.idle for r in self.results.values())


class FleetRouter:
    """N schedulers + one handoff plane (see module docstring).
    Single-threaded like the schedulers it drives; ``submit`` is as
    thread-safe as theirs."""

    def __init__(self, replicas, *,
                 plane: handoff_mod.HandoffPlane | None = None,
                 config: FleetConfig | None = None):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        seen: set[str] = set()
        for rep in replicas:
            if rep.role not in ("prefill", "decode"):
                raise ValueError(
                    f"replica {rep.replica_id!r}: role must be "
                    f"'prefill' or 'decode', got {rep.role!r}")
            if rep.replica_id in seen:
                raise ValueError(
                    f"duplicate replica id {rep.replica_id!r} — ids key "
                    f"breakers, gauges and page-lifecycle pools, they "
                    f"must be unique")
            seen.add(rep.replica_id)
            want = rep.role == "prefill"
            if rep.scheduler.cfg.prefill_only != want:
                raise ValueError(
                    f"replica {rep.replica_id!r} has role {rep.role!r} "
                    f"but its SchedulerConfig.prefill_only is "
                    f"{rep.scheduler.cfg.prefill_only} — a prefill "
                    f"replica must park finished prompts in HANDOFF "
                    f"(prefill_only=True) and a decode replica must "
                    f"decode locally (prefill_only=False)")
        for role in ("prefill", "decode"):
            if not any(r.role == role for r in replicas):
                raise ValueError(
                    f"a fleet needs at least one {role!r}-role replica")
        # page GEOMETRY must match fleet-wide for a handoff implant (and
        # a failover re-prefill's stamp carry) to land on ANY member —
        # the DisaggRouter check, applied pairwise against replica 0
        k0 = replicas[0].scheduler.cache.k
        for rep in replicas[1:]:
            k = rep.scheduler.cache.k
            if (k0.shape[0], k0.shape[2:]) != (k.shape[0], k.shape[2:]):
                raise ValueError(
                    f"replica {rep.replica_id!r} page geometry "
                    f"(layers={k.shape[0]}, kv_heads={k.shape[2]}, "
                    f"page_size={k.shape[3]}, head_dim={k.shape[4]}) "
                    f"differs from replica "
                    f"{replicas[0].replica_id!r}'s — a handoff payload "
                    f"cannot be implanted across page shapes (pool "
                    f"SIZES and kv dtypes may differ freely)")
        self.replicas = replicas
        self._by_id = {r.replica_id: r for r in replicas}
        # request traces name the REPLICA each hop ran on
        for rep in replicas:
            rep.scheduler.trace_tier = rep.replica_id
        # the re-prefill/failover stamp carry only pins a recompute on a
        # pool with the SAME layout (router.py's rule, fleet-wide)
        self._stamp_carry_ok = all(
            rep.scheduler.cache.k.dtype == k0.dtype
            and rep.scheduler.cache.quantized
            == replicas[0].scheduler.cache.quantized
            for rep in replicas)
        self.plane = plane if plane is not None \
            else handoff_mod.HandoffPlane()
        self.cfg = config or FleetConfig()
        self.steps = 0
        self.handoffs = 0
        self.colocated = 0
        self.reprefills = 0
        self.aborts = 0
        self.failovers = 0
        self.failover_shed = 0
        self.reprefill_ids: set[int] = set()
        self.failover_ids: set[int] = set()
        self.lost_replicas: list[str] = []
        self.quarantined_history: list[str] = []
        self.readmissions: list[str] = []
        self.rebalances: list[dict] = []
        self.last_convergence_steps: int | None = None
        self._park_strikes: dict[int, int] = {}
        # session affinity: session key -> replica id where its pages
        # (or its conversation's most recent pages) live
        self._affinity: dict[str, str] = {}
        self._session_of: dict[int, str] = {}
        self._failover_count: dict[int, int] = {}
        # pending role recruit: (replica, target role, detection step)
        self._recruit: tuple[Replica, str, int] | None = None
        self._dom_role: str | None = None
        self._dom_count = 0
        self._dom_first_step = 0
        # the fleet observability plane (TDT_FLEET_OBS=1): per-replica
        # tee collectors + fleet-merged windows; None when off, and
        # nothing above pays for it
        self.fleet_stats = fleet_obs.attach(self)

    def _decide(self, kind: str, **kw) -> None:
        """Ledger one control-plane actuation (``obs.decisions``).
        Every call site gates on ``decisions.enabled()`` BEFORE
        building its inputs dict, so an unarmed fleet pays one bool
        read per actuation; ``analysis.completeness`` pins these sites
        against the ``DECISION_KINDS`` golden both directions."""
        decisions.record(kind, step=self.steps, **kw)

    # -- membership predicates ---------------------------------------------

    def _admitting(self, rep: Replica) -> bool:
        """May new work land on this replica?  Membership flags plus
        the live breaker — an open ``replica:<id>`` breaker refuses
        admission even before the quarantine tick flips the flag."""
        from .. import resilience

        if rep.draining or rep.evicted or rep.lost:
            return False
        return not resilience.breaker(
            replica_breaker_name(rep.replica_id),
            self.cfg.flap_threshold).open

    def _steppable(self, rep: Replica) -> bool:
        return not (rep.lost or rep.evicted)

    def _pressured(self, sched: Scheduler) -> bool:
        if sched._saturated_since is not None:
            return True
        q = sched.queue.depth / sched.queue.max_depth
        return (q >= self.cfg.queue_pressure
                or sched.pool.occupancy() >= self.cfg.pool_pressure)

    def _load(self, sched: Scheduler) -> float:
        """The routing score: the SAME queue-depth fraction and pool
        occupancy the replica's gauges publish."""
        return (sched.queue.depth / sched.queue.max_depth
                + sched.pool.occupancy())

    def _fits(self, rep: Replica, req: Request) -> bool:
        """Never-fits + queue-room screen (``Scheduler.submit`` would
        shed; routing there would convert a survivable failover into a
        terminal shed)."""
        sched = rep.scheduler
        total = req.prompt_len + req.max_new_tokens
        return (total <= sched.backend.max_length
                and pages_needed(total, sched.pool.page_size)
                <= sched.pool.capacity
                and sched.queue.depth < sched.queue.max_depth)

    def _candidates(self, role: str, *, exclude: str | None = None,
                    req: Request | None = None) -> list[Replica]:
        out = [rep for rep in self.replicas
               if rep.role == role and self._admitting(rep)
               and rep.replica_id != exclude
               and (req is None or self._fits(rep, req))]
        out.sort(key=lambda r: (self._load(r.scheduler), r.replica_id))
        return out

    # -- admission routing -------------------------------------------------

    def submit(self, req: Request, *, session: str | None = None,
               now: float | None = None) -> bool:
        """Telemetry-driven admission: session affinity first (the
        session's pages live there), else the least-loaded admitting
        prefill replica, else — every prefill replica pressured or
        quarantined — the least-loaded admitting decode replica runs it
        COLOCATED.  No admitting replica anywhere -> terminal shed."""
        home: str | None = None
        if session is not None:
            self._session_of[req.req_id] = session
            home = self._affinity.get(session)
            rep = self._by_id.get(home) if home is not None else None
            if rep is not None and self._admitting(rep) \
                    and self._fits(rep, req) \
                    and not self._pressured(rep.scheduler):
                if obs.enabled():
                    obs.counter("fleet_affinity_hits").inc()
                if decisions.enabled():
                    self._decide(
                        "affinity_hit", replica=rep.replica_id,
                        request_id=req.req_id, session=session,
                        inputs={"home": home,
                                "load": self._load(rep.scheduler),
                                "pressured": False, "role": rep.role})
                if rep.role == "decode":
                    self.colocated += 1
                return rep.scheduler.submit(req, now=now)
        prefills = self._candidates("prefill", req=req)
        unpressured = [r for r in prefills
                       if not self._pressured(r.scheduler)]
        target = (unpressured or prefills)[0] if (unpressured or prefills) \
            else None
        if target is not None and self._pressured(target.scheduler):
            # every admitting prefill replica is pressured: colocate on
            # a healthy decode replica instead (the DisaggRouter move,
            # fleet-wide)
            decodes = [r for r in self._candidates("decode", req=req)
                       if not self._pressured(r.scheduler)]
            if decodes:
                target = decodes[0]
        if target is None:
            decodes = self._candidates("decode", req=req)
            target = decodes[0] if decodes else None
        if target is None:
            # no admitting replica can ever hold it: the fleet-level
            # backpressure terminal, accounted like a queue shed
            obs.request_trace.maybe_begin(req, "fleet")
            req.state = RequestState.SHED
            req.shed_reason = "no admitting replica in any role"
            req.finished_s = time.monotonic() if now is None else now
            obs.request_trace.finish(req)
            if obs.enabled():
                obs.serve_stats.STATS.request_shed()
                obs.counter("fleet_shed_no_replica").inc()
            if decisions.enabled():
                self._decide(
                    "shed", request_id=req.req_id, session=session,
                    inputs={"reason": req.shed_reason,
                            "prompt_len": req.prompt_len,
                            "max_new_tokens": req.max_new_tokens})
            return False
        if decisions.enabled():
            inputs = {"home": home, "role": target.role,
                      "load": self._load(target.scheduler),
                      "pressured": self._pressured(target.scheduler)}
            if home is not None and home != target.replica_id:
                # the session HAD a home replica and didn't get it
                self._decide(
                    "affinity_redirect", replica=target.replica_id,
                    request_id=req.req_id, session=session,
                    inputs=inputs)
            else:
                self._decide(
                    "route", replica=target.replica_id,
                    request_id=req.req_id, session=session,
                    inputs=inputs)
        if target.role == "decode":
            self.colocated += 1
            if obs.enabled():
                obs.counter("router_colocated_submits").inc()
        ok = target.scheduler.submit(req, now=now)
        if ok and session is not None:
            self._affinity[session] = target.replica_id
        return ok

    # -- the step ----------------------------------------------------------

    def step(self) -> FleetStepResult:
        h0, c0, r0, f0 = (self.handoffs, self.colocated, self.reprefills,
                          self.failovers)
        self.steps += 1
        results: dict[str, StepResult] = {}
        # prefill-role replicas first (draining ones still step — they
        # finish residents; evicted/lost ones don't)
        for rep in self.replicas:
            if rep.role == "prefill" and self._steppable(rep):
                results[rep.replica_id] = rep.scheduler.step()
                self._watch_failures(rep)
        self._pump_handoffs()
        obs.continuous.on_step("handoff", self.steps)
        for rep in self.replicas:
            if rep.role == "decode" and self._steppable(rep):
                results[rep.replica_id] = rep.scheduler.step()
                self._watch_failures(rep)
        wire = getattr(self.plane.dcn, "wire", None)
        if wire is not None:
            wire.tick(self.cfg.step_wall_ms)
        self._quarantine_tick()
        self._probe_tick()
        if self.cfg.rebalance_enabled:
            self._rebalance_tick()
        self._publish_gauges()
        if self.fleet_stats is not None:
            self.fleet_stats.on_step(self.steps, router=self)
        return FleetStepResult(
            results=results,
            handoffs=self.handoffs - h0,
            colocated=self.colocated - c0,
            reprefills=self.reprefills - r0,
            failovers=self.failovers - f0,
        )

    def run_until_idle(self, *, max_steps: int = 100_000) -> int:
        for _ in range(max_steps):
            if self.step().idle:
                return self.steps
        raise RuntimeError(
            f"fleet not idle after {max_steps} steps: "
            f"{self.debug_state()}")

    # -- replica loss + flap failover --------------------------------------

    def lose_replica(self, replica_id: str, *,
                     reason: str = "replica lost") -> list[int]:
        """Hard loss mid-flight (crash, partition): evict the replica,
        reclaim every resident page (the lifecycle recorder sees the
        frees — nothing leaks with the pool), and re-prefill every
        resident and queued request on a survivor.  Audit stamps carry
        on ``Request.kv_stamps`` (the ``_preempt_slot`` rule) so the
        recompute is verified like a preemption restore; the original
        submit clock and trace chain ride along.  Returns the moved
        request ids."""
        from .. import resilience
        from ..resilience import integrity

        rep = self._by_id[replica_id]
        if rep.lost:
            return []
        if decisions.enabled():
            self._decide(
                "replica_lost", replica=replica_id,
                inputs={"reason": reason,
                        "residents": sum(
                            1 for s in rep.scheduler.slots
                            if s is not None),
                        "queue_depth": rep.scheduler.queue.depth,
                        "stamp_carry_ok": self._stamp_carry_ok})
        rep.lost = True
        rep.evicted = True
        rep.draining = True
        self.lost_replicas.append(replica_id)
        # walk the replica breaker fully open: membership math (and the
        # health snapshot's quarantined_replicas) treats a lost replica
        # as permanently quarantined — probes skip it, only an operator
        # replacing the replica object brings the id back
        br = resilience.breaker(replica_breaker_name(replica_id),
                                self.cfg.flap_threshold)
        while not br.open:
            br.record_failure()
        sched = rep.scheduler
        moved: list[int] = []
        for i, slot in enumerate(sched.slots):
            if slot is None:
                continue
            req = slot.request
            if integrity.enabled() and slot.page_stamps \
                    and self._stamp_carry_ok and req.kv_stamps is None:
                full_prompt = req.prompt_len // sched.pool.page_size
                carry = {j: s for j, s in slot.page_stamps.items()
                         if j < full_prompt}
                req.kv_stamps = carry or None
            sched._release_slot(i)
            if self._failover(req, from_rid=replica_id, reason=reason,
                              reopen=False):
                moved.append(req.req_id)
        while True:
            req = sched.queue.pop()
            if req is None:
                break
            if self._failover(req, from_rid=replica_id, reason=reason,
                              reopen=False):
                moved.append(req.req_id)
        if obs.enabled():
            obs.counter("fleet_replicas_lost").inc()
        return moved

    def _watch_failures(self, rep: Replica) -> None:
        """The flap watcher: every NEW terminal failure on this replica
        feeds its sticky breaker (deadline breaches excepted — those
        are the request's SLO, not replica health) and rides the
        failover ladder onto a survivor."""
        from .. import resilience

        new = rep.scheduler.failed[rep._seen_failed:]
        rep._seen_failed = len(rep.scheduler.failed)
        for req in new:
            if (req.error or "").startswith("deadline"):
                continue
            opened = resilience.breaker(
                replica_breaker_name(rep.replica_id),
                self.cfg.flap_threshold).record_failure()
            if opened and not rep.draining:
                rep.draining = True
                if obs.enabled():
                    obs.counter("fleet_quarantine_drains").inc()
                if decisions.enabled():
                    # the failing request's trace id IS the exemplar
                    # that drove the quarantine — the lint replay
                    # asserts it resolves in the trace ring
                    self._decide(
                        "quarantine_drain", replica=rep.replica_id,
                        request_id=req.req_id,
                        inputs={"error": req.error,
                                "flap_threshold":
                                    self.cfg.flap_threshold,
                                "exemplar": getattr(
                                    req.trace, "trace_id", None)})
            if self._failover_count.get(req.req_id, 0) \
                    >= self.cfg.max_failovers_per_request:
                continue   # replaying it again would replay the fault
            self._failover(req, from_rid=rep.replica_id,
                           reason=f"step failure on replica "
                                  f"{rep.replica_id}: {req.error}",
                           reopen=True)

    def _failover(self, req: Request, *, from_rid: str, reason: str,
                  reopen: bool) -> bool:
        """Resubmit one displaced request on a survivor.  The ORIGINAL
        submit timestamp survives (``RequestQueue.submit`` only stamps
        ``submitted_s`` when unset) so the ``ttft_ms``/``request_ms``
        sketches account the lost replica's time; ``reopen=True``
        additionally un-closes a trace ``_fail_slot`` already finished,
        so the resubmit's ``queue_wait`` extends the SAME gapless
        chain with a ``resubmit`` tag."""
        self._failover_count[req.req_id] = \
            self._failover_count.get(req.req_id, 0) + 1
        req.error = None
        req.shed_reason = None
        req.finished_s = None
        req.tokens = []   # deterministic recompute from the prompt
        if reopen:
            obs.request_trace.reopen_for_failover(req)
        if req.trace is not None:
            req.trace.annotate("failover", tier=from_rid, reason=reason)
        targets = (self._candidates("decode", exclude=from_rid, req=req)
                   or self._candidates("prefill", exclude=from_rid,
                                       req=req))
        if not targets:
            # no survivor can hold it: terminal shed, accounted at the
            # fleet level — the pages were already reclaimed
            req.state = RequestState.SHED
            req.shed_reason = (f"no survivor replica can hold the "
                               f"request after failover ({reason})")
            req.finished_s = time.monotonic()
            obs.request_trace.finish(req)
            if obs.enabled():
                obs.serve_stats.STATS.request_shed()
                obs.counter("fleet_failover_shed").inc()
            if decisions.enabled():
                self._decide(
                    "failover_shed", replica=from_rid,
                    request_id=req.req_id,
                    inputs={"reason": reason,
                            "failover_count":
                                self._failover_count[req.req_id]})
            self.failover_shed += 1
            return False
        target = targets[0]
        self.failovers += 1
        self.failover_ids.add(req.req_id)
        if obs.enabled():
            obs.counter("fleet_failovers").inc()
        if decisions.enabled():
            self._decide(
                "failover", replica=target.replica_id,
                request_id=req.req_id,
                inputs={"from": from_rid, "to": target.replica_id,
                        "reason": reason,
                        "load": self._load(target.scheduler),
                        "failover_count":
                            self._failover_count[req.req_id]})
        ok = target.scheduler.submit(req)
        if ok:
            sess = self._session_of.get(req.req_id)
            if sess is not None:
                self._affinity[sess] = target.replica_id
        return ok

    # -- quarantine / readmission ------------------------------------------

    def _drained(self, rep: Replica) -> bool:
        sched = rep.scheduler
        return (sched.queue.depth == 0
                and all(s is None for s in sched.slots))

    def _quarantine_tick(self) -> None:
        """Drain-before-evict: an open breaker flips the replica to
        draining (admission refused, residents finish); once drained it
        evicts from membership and waits for probes."""
        from .. import resilience

        for rep in self.replicas:
            if rep.lost or rep.recruiting:
                continue
            br = resilience.breaker(
                replica_breaker_name(rep.replica_id),
                self.cfg.flap_threshold)
            if not br.open:
                continue
            if not rep.draining:
                rep.draining = True
                if obs.enabled():
                    obs.counter("fleet_quarantine_drains").inc()
                if decisions.enabled():
                    # breaker walked open outside the flap watcher
                    # (e.g. failed readmission probes): the best
                    # exemplar is the live p99's
                    self._decide(
                        "quarantine_drain", replica=rep.replica_id,
                        inputs={"flap_threshold":
                                    self.cfg.flap_threshold,
                                "exemplar": obs.serve_stats.STATS
                                    .request_ms.exemplar(0.99)})
            if not rep.evicted and self._drained(rep):
                rep.evicted = True
                rep.probe_successes = 0
                self.quarantined_history.append(rep.replica_id)
                if obs.enabled():
                    obs.counter("fleet_quarantine_evictions").inc()
                if decisions.enabled():
                    self._decide(
                        "quarantine_evict", replica=rep.replica_id,
                        inputs={"drained": True,
                                "probe_interval_steps":
                                    self.cfg.probe_interval_steps})

    def _probe_tick(self) -> None:
        """Readmission probes: every ``probe_interval_steps`` each
        quarantined (evicted, not lost) replica runs one suppressed
        probe request end-to-end; ``readmit_probe_successes``
        consecutive greens readmit it, any red resets the count and
        re-feeds the breaker."""
        from .. import resilience

        if self.steps % self.cfg.probe_interval_steps != 0:
            return
        for rep in self.replicas:
            if not rep.quarantined:
                continue
            ok = self._probe(rep)
            if decisions.enabled():
                # recorded OUTSIDE the suppressed probe run: the probe
                # traffic stays out of the sketches, the DECISION to
                # probe (and its outcome) lands in the ledger
                self._decide(
                    "readmit_probe", replica=rep.replica_id,
                    inputs={"ok": ok,
                            "probe_successes": rep.probe_successes,
                            "interval":
                                self.cfg.probe_interval_steps})
            if ok:
                rep.probe_successes += 1
                if rep.probe_successes >= self.cfg.readmit_probe_successes:
                    self.readmit(rep.replica_id)
            else:
                rep.probe_successes = 0
                resilience.breaker(
                    replica_breaker_name(rep.replica_id),
                    self.cfg.flap_threshold).record_failure()

    def _probe(self, rep: Replica) -> bool:
        """One canary request driven to a terminal state on the
        quarantined replica, under ``obs.suppress()`` so probe traffic
        never lands in the latency sketches or mints traces."""
        sched = rep.scheduler
        probe = Request(
            prompt=(1, 2, 3),
            max_new_tokens=1 if sched.cfg.prefill_only else 2)
        ok = False
        with obs.suppress():
            if sched.submit(probe):
                for _ in range(self.cfg.probe_max_steps):
                    sched.step()
                    if probe.state is RequestState.DONE:
                        ok = True
                        break
                    if probe.state in (RequestState.FAILED,
                                       RequestState.SHED):
                        break
        # probe outcomes must not feed the flap watcher as tenant
        # failures — the probe loop scores them itself
        rep._seen_failed = len(sched.failed)
        if obs.enabled():
            obs.counter("fleet_probes",
                        outcome="ok" if ok else "failed").inc()
        return ok

    def readmit(self, replica_id: str) -> None:
        """Re-enter membership after probation: breaker reset, flags
        cleared; the replica starts taking new admissions next step."""
        from .. import resilience

        rep = self._by_id[replica_id]
        if rep.lost:
            raise ValueError(
                f"replica {replica_id!r} was LOST, not quarantined — "
                f"readmission needs a replacement replica, not a "
                f"breaker reset")
        if decisions.enabled():
            self._decide(
                "readmit", replica=replica_id,
                inputs={"probe_successes": rep.probe_successes,
                        "required":
                            self.cfg.readmit_probe_successes})
        resilience.reset_breaker(replica_breaker_name(replica_id))
        rep.draining = False
        rep.evicted = False
        rep.probe_successes = 0
        self.readmissions.append(replica_id)
        if obs.enabled():
            obs.counter("fleet_readmissions").inc()

    # -- SLO-driven rebalance ----------------------------------------------

    def _role_pressured(self, role: str) -> bool:
        admitting = [r for r in self.replicas
                     if r.role == role and self._admitting(r)]
        return bool(admitting) and all(
            self._pressured(r.scheduler) for r in admitting)

    def _dominant_role_demand(self, detail: dict | None = None) \
            -> str | None:
        """The measurement half of the loop: the attributor's
        ``dominant_phase`` over the live p99 sketch exemplars,
        cross-checked against role-wide pressure.  Decode demand reads
        the ``request_ms`` p99: ``decode`` dominance directly, but also
        ``preempted`` (decode-pool thrash — eviction-recompute cycles
        ARE decode-capacity shortage) and ``handoff`` (prompts parked
        because no decode replica can adopt).  Prefill demand reads the
        ``ttft_ms`` p99: ``prefill`` or ``queue`` dominance with the
        prefill role pressured.  ``detail`` (when given) is filled with
        the inputs actually read — exemplar ids, dominant phases, role
        pressure — verbatim for the decision ledger."""
        from ..obs import request_trace as rtrace

        stats = obs.serve_stats.STATS

        def dom(sketch, label):
            ex = sketch.exemplar(0.99)
            if detail is not None:
                detail[f"{label}_exemplar"] = ex
            if ex is None:
                return None
            tr = rtrace.RING.get(ex)
            if tr is None:
                return None
            phase = rtrace.attribute_request(tr).get("dominant_phase")
            if detail is not None:
                detail[f"{label}_dominant_phase"] = phase
            return phase

        decode_pressured = self._role_pressured("decode")
        prefill_pressured = self._role_pressured("prefill")
        if detail is not None:
            detail["decode_pressured"] = decode_pressured
            detail["prefill_pressured"] = prefill_pressured
        if decode_pressured:
            d = dom(stats.request_ms, "request_ms")
            if d in ("decode", "preempted", "handoff"):
                return "decode"
            # queue-dominated end-to-end p99 with the decode role
            # saturated and the prefill role healthy: the queue is
            # backing up BEHIND the saturated decode tier (prefill
            # slots parked in handoff with nowhere to adopt), so the
            # binding constraint is still decode capacity
            if d == "queue" and not prefill_pressured:
                return "decode"
        if prefill_pressured \
                and dom(stats.ttft_ms, "ttft_ms") in ("prefill",
                                                      "queue"):
            return "prefill"
        return None

    def _rebalance_tick(self) -> None:
        # a pending recruit converts the moment its donor drains —
        # residents finish under the OLD role (drain-before-convert);
        # one conversion in flight at a time
        if self._recruit is not None:
            rep, to_role, first_seen = self._recruit
            if self._drained(rep):
                self._convert(rep, to_role, first_seen)
                self._recruit = None
            return
        if self.steps % self.cfg.rebalance_interval_steps != 0:
            return
        detail: dict | None = {} if decisions.enabled() else None
        want = self._dominant_role_demand(detail)
        if want is None:
            # the demand read is SPARSE (the p99 exemplar only moves
            # when a request completes; pressure flickers as pools
            # drain): a quiet tick neither confirms nor refutes the
            # streak, so it doesn't reset it — only a CONTRARY read
            # does
            return
        if want != self._dom_role:
            self._dom_role = want
            self._dom_count = 1
            self._dom_first_step = self.steps
            if detail is not None:
                self._decide(
                    "rebalance_streak",
                    inputs={"want": want, "streak": 1,
                            "sustain": self.cfg.rebalance_sustain,
                            **detail})
            return
        self._dom_count += 1
        if detail is not None:
            self._decide(
                "rebalance_streak",
                inputs={"want": want, "streak": self._dom_count,
                        "sustain": self.cfg.rebalance_sustain,
                        **detail})
        if self._dom_count < self.cfg.rebalance_sustain:
            return
        donor_role = "prefill" if want == "decode" else "decode"
        donors = self._candidates(donor_role)
        # the donor role must keep at least one admitting replica — a
        # rebalance that empties a role trades saturation for outage
        if len(donors) < 2:
            return
        donor = donors[0]   # least loaded = fastest to drain
        donor.recruiting = True
        donor.draining = True
        self._recruit = (donor, want, self._dom_first_step)
        if detail is not None:
            self._decide(
                "recruit", replica=donor.replica_id,
                inputs={"role": want, "donor_role": donor_role,
                        "streak": self._dom_count,
                        "first_seen_step": self._dom_first_step,
                        "donor_load": self._load(donor.scheduler),
                        **detail})
        self._dom_role = None
        self._dom_count = 0
        if obs.enabled():
            obs.counter("fleet_recruits", role=want).inc()

    def _convert(self, rep: Replica, to_role: str,
                 first_seen: int) -> None:
        from_role = rep.role
        rep.scheduler.cfg = dataclasses.replace(
            rep.scheduler.cfg, prefill_only=(to_role == "prefill"))
        rep.role = to_role
        rep.recruiting = False
        rep.draining = False
        steps = self.steps - first_seen
        self.last_convergence_steps = steps
        self.rebalances.append({
            "replica": rep.replica_id, "from": from_role, "to": to_role,
            "step": self.steps, "convergence_steps": steps,
        })
        if decisions.enabled():
            self._decide(
                "convert", replica=rep.replica_id,
                inputs={"from": from_role, "to": to_role,
                        "convergence_steps": steps})
        if self.fleet_stats is not None:
            self.fleet_stats.set_role(rep.replica_id, to_role)
        if obs.enabled():
            obs.counter("fleet_rebalances").inc()
            obs.serve_stats.STATS.set_gauge(
                "fleet_rebalance_convergence_steps", float(steps))

    # -- the handoff pump ---------------------------------------------------

    def _pump_handoffs(self) -> None:
        with obs.span("router_pump", "step"):
            self._pump_handoffs_impl()

    def _pump_handoffs_impl(self) -> None:
        from ..comm import dcn
        from ..resilience.faults import RankAborted

        if self.cfg.bulk_bytes_per_step:
            wire = getattr(self.plane.dcn, "wire", None)
            if wire is not None:
                wire.send(self.cfg.bulk_bytes_per_step,
                          priority=dcn.BULK)
        budget = self.cfg.max_transfers_per_step
        for rep in self.replicas:
            if rep.role != "prefill" or not self._steppable(rep):
                continue
            if budget <= 0:
                break
            sched = rep.scheduler
            for i in sched.handoff_ready():
                if budget <= 0:
                    break
                budget -= 1
                slot = sched.slots[i]
                req = slot.request
                target = self._adopt_target(rep, req)
                if target is None:
                    # no decode replica can take it: wait out a
                    # transient busy spell, then shed back to colocated
                    # mode BEFORE paying the wire
                    strikes = self._park_strikes.get(req.req_id, 0) + 1
                    self._park_strikes[req.req_id] = strikes
                    if self.cfg.colocate_on_saturation and \
                            strikes > self.cfg.adopt_patience_steps:
                        self._park_strikes.pop(req.req_id, None)
                        self._colocate(rep, i, req)
                    continue
                self._park_strikes.pop(req.req_id, None)
                tr = req.trace
                if tr is not None:
                    tr.begin("handoff_extract", tier=rep.replica_id)
                payload = handoff_mod.extract_payload(
                    sched.cache, slot.pages, req, slot.next_token,
                    wire_dtype=self.plane.cfg.wire_dtype,
                    pool=sched.pool)
                if tr is not None:
                    tr.begin("handoff_transfer", tier=rep.replica_id,
                             pages=payload.n_pages,
                             bytes=payload.payload_bytes,
                             wire=payload.wire, target=target.replica_id)
                try:
                    arrived = self.plane.transfer(payload, trace=tr)
                except RankAborted as e:
                    self.aborts += 1
                    if obs.enabled():
                        obs.counter("handoff_aborts").inc()
                    self._reprefill(rep, i, req, payload,
                                    reason=f"prefill replica "
                                           f"{rep.replica_id} aborted "
                                           f"mid-handoff ({e})")
                    continue
                if arrived is None:
                    self._reprefill(rep, i, req, payload,
                                    reason="transfer ladder exhausted")
                    continue
                dsched = target.scheduler
                adopted = dsched.adopt_prefilled(
                    req,
                    lambda cache, pages: handoff_mod.implant_payload(
                        cache, pages, arrived, pool=dsched.pool),
                    length=arrived.prompt_len,
                    next_token=arrived.first_token)
                if adopted:
                    sched.release_handoff(i)
                    self.handoffs += 1
                    sess = self._session_of.get(req.req_id)
                    if sess is not None:
                        self._affinity[sess] = target.replica_id
                elif self.cfg.colocate_on_saturation:
                    self._colocate(rep, i, req)
                # else: stay parked; retried next step

    def _adopt_target(self, src: Replica, req: Request) -> Replica | None:
        """Where should this handoff land?  Session affinity first —
        the session's earlier pages live there — else the least-loaded
        admitting decode replica whose admission policy says yes."""
        sess = self._session_of.get(req.req_id)
        home = self._affinity.get(sess) if sess is not None else None
        if home is not None and home != src.replica_id:
            rep = self._by_id.get(home)
            if rep is not None and rep.role == "decode" \
                    and self._admitting(rep) \
                    and rep.scheduler.can_adopt(req):
                return rep
        for rep in self._candidates("decode", exclude=src.replica_id):
            if rep.scheduler.can_adopt(req):
                return rep
        return None

    def _colocate(self, rep: Replica, i: int, req: Request) -> None:
        if decisions.enabled():
            self._decide(
                "colocate", replica=rep.replica_id,
                request_id=req.req_id,
                inputs={"occupancy": rep.scheduler.pool.occupancy(),
                        "queue_depth": rep.scheduler.queue.depth})
        rep.scheduler.colocate(i)
        self.colocated += 1
        sess = self._session_of.get(req.req_id)
        if sess is not None:
            self._affinity[sess] = rep.replica_id

    def _reprefill(self, rep: Replica, i: int, req: Request,
                   payload: handoff_mod.PagePayload, *,
                   reason: str) -> None:
        """The terminal fallback, fleet-wide: recompute the prompt on a
        decode replica, verified against the producer's page stamps
        exactly like a preemption restore."""
        from ..resilience import integrity

        targets = self._candidates("decode", exclude=rep.replica_id,
                                   req=req)
        if not targets:
            # nowhere to recompute: colocating loses nothing — the
            # pages are still in this replica's pool
            self._colocate(rep, i, req)
            return
        target = targets[0]
        req.tokens = []
        if integrity.enabled() and payload.cache_stamps \
                and self._stamp_carry_ok and req.kv_stamps is None:
            req.kv_stamps = dict(payload.cache_stamps)
        rep.scheduler.release_handoff(i)
        self.reprefills += 1
        self.reprefill_ids.add(req.req_id)
        if req.trace is not None:
            req.trace.annotate("reprefill", tier=target.replica_id,
                               reason=reason)
        if decisions.enabled():
            self._decide(
                "reprefill", replica=target.replica_id,
                request_id=req.req_id,
                inputs={"from": rep.replica_id, "reason": reason,
                        "pages": payload.n_pages,
                        "stamp_carry": req.kv_stamps is not None})
        if obs.enabled():
            obs.counter("handoff_reprefills").inc()
        if target.scheduler.submit(req):
            sess = self._session_of.get(req.req_id)
            if sess is not None:
                self._affinity[sess] = target.replica_id
        elif obs.enabled():
            obs.counter("handoff_reprefill_shed").inc()

    # -- health / introspection --------------------------------------------

    def health(self) -> dict:
        """The fleet-aggregated ``/healthz`` payload: the process
        resilience snapshot (now carrying ``quarantined_replicas``),
        live serve stats, every replica's membership + scheduler state,
        and the role-availability aggregation — ``status`` leaves "ok"
        for "unavailable" (503) while ANY role has zero admitting
        replicas, and for "saturated" (503) while any admitting replica
        is under sustained pool pressure."""
        from .. import resilience

        snap = resilience.health_snapshot()
        snap["serve_stats"] = obs.serve_stats.STATS.snapshot()
        snap["replicas"] = {
            rep.replica_id: {
                "role": rep.role,
                "draining": rep.draining,
                "evicted": rep.evicted,
                "lost": rep.lost,
                "recruiting": rep.recruiting,
                "quarantined": rep.quarantined,
                "admitting": self._admitting(rep),
                "scheduler": rep.scheduler.debug_state(),
            }
            for rep in self.replicas
        }
        snap["fleet"] = self.snapshot()
        saturated = [
            rep.replica_id for rep in self.replicas
            if self._steppable(rep)
            and rep.scheduler._saturated_since is not None
            and rep.scheduler.saturated_s()
            >= rep.scheduler.cfg.saturation_sustain_s
        ]
        snap["saturated_replicas"] = saturated
        unavailable = [
            role for role in ("prefill", "decode")
            if not any(rep.role == role and self._admitting(rep)
                       for rep in self.replicas)
        ]
        snap["unavailable_roles"] = unavailable
        if unavailable:
            snap["status"] = "unavailable"
        elif snap["status"] == "ok" and saturated:
            snap["status"] = "saturated"
        if self.fleet_stats is not None:
            frag = self.fleet_stats.health_fragment()
            if frag is not None:
                # a WARNING, never a status flip: fleet-scope drift is
                # an operator signal, not an outage (the PR-15 rule —
                # drift never 503s)
                snap["fleet_obs"] = frag
        return snap

    def snapshot(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "roles": {role: sum(1 for r in self.replicas
                                if r.role == role and self._admitting(r))
                      for role in ("prefill", "decode")},
            "handoffs": self.handoffs,
            "colocated": self.colocated,
            "reprefills": self.reprefills,
            "aborts": self.aborts,
            "failovers": self.failovers,
            "failover_shed": self.failover_shed,
            "lost_replicas": list(self.lost_replicas),
            "quarantined": [r.replica_id for r in self.replicas
                            if r.quarantined],
            "readmissions": list(self.readmissions),
            "rebalances": list(self.rebalances),
            "last_convergence_steps": self.last_convergence_steps,
            "plane": self.plane.snapshot(),
        }

    def debug_state(self) -> dict:
        return {
            "fleet": self.snapshot(),
            "replicas": {
                rep.replica_id: rep.scheduler.debug_state()
                for rep in self.replicas
            },
        }

    def leaked_pages(self) -> int:
        """Used pages across EVERY replica once everything drained —
        the zero-leak invariant ``tdt_lint --fleet`` gates per replica
        (a lost replica's pool was reclaimed at loss time, so it
        counts too)."""
        return sum(rep.scheduler.pool.used_pages
                   for rep in self.replicas)

    def _publish_gauges(self) -> None:
        if not obs.enabled():
            return
        stats = obs.serve_stats.STATS
        # per-replica labels ride the gauge NAME (the stats block's
        # flat-gauge rendering; the replica id is the label)
        for rep in self.replicas:
            rid = rep.replica_id
            sched = rep.scheduler
            stats.set_gauge(f"replica_{rid}_queue_depth",
                            float(sched.queue.depth))
            stats.set_gauge(f"replica_{rid}_pool_occupancy",
                            sched.pool.occupancy())
            stats.set_gauge(f"replica_{rid}_admitting",
                            1.0 if self._admitting(rep) else 0.0)
        stats.set_gauge("fleet_admitting_replicas",
                        float(sum(1 for r in self.replicas
                                  if self._admitting(r))))
