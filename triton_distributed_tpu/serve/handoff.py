"""The fault-tolerant KV-handoff plane of disaggregated serving.

Disaggregation (``serve.router``) splits the serving topology into a
prefill-specialized tier and a decode-specialized tier; what crosses
the DCN between them is a finished prompt's KV pages.  The wire part is
cheap — PR 10 built a calibrated, quantized, integrity-checked DCN
layer — the hard part is SURVIVING it, and that is this module:

- **Payload** (:class:`PagePayload` / :func:`extract_payload` /
  :func:`implant_payload`): the prompt's physical pages pulled from the
  producer pool, shipped int8 + f32 scale sidecars when
  ``tools.calibrate.codec_pays("dcn")`` says the codec wins net wire
  time (an int8 KV pool ships its pages + sidecars verbatim), and
  implanted into the consumer pool whatever ITS layout is (float pools
  dequantize on arrival, int8 pools requantize at (page, head)
  granularity).
- **Stamps**: every page is ``fold32``-stamped over its WIRE bytes at
  the producer (PR-7 integrity plane — scale sidecars fold in, a
  flipped sidecar byte corrupts the whole (page, head) block on
  dequant) and re-folded at the consumer before implant; a mismatch is
  a named :class:`~..resilience.errors.PayloadCorruption` carrying the
  page.  Separately, ``cache_stamps`` fold the producer's POOL bytes of
  every full prompt page: they ride ``Request.kv_stamps`` into the
  re-prefill fallback so a recomputed cache is verified exactly like a
  preemption restore (``Scheduler._verify_restore``).
- **The ladder** (:meth:`HandoffPlane.transfer`): each transfer runs
  under a SOL-priced watchdog deadline (``resilience.deadline_ms``
  prices the payload over the calibrated DCN rate) down the standard
  failure ladder — bounded retry with backoff, then ``None`` as the
  ladder bottom, which the router converts into the terminal fallback:
  RE-PREFILL on the decode tier.  Repeated ladder-bottom failures walk
  the sticky ``handoff_transfer`` circuit breaker open, after which
  transfers skip the sick wire entirely (every request re-prefills)
  until an operator resets it — and ``/healthz`` reports the op
  degraded meanwhile.
- **Priority**: transfers ship :data:`~..comm.dcn.LATENCY` class on the
  shared wire (``comm.dcn.PriorityDCNWire``) — a decode slot is idle
  until its pages arrive, so handoff pages preempt bulk prefill
  streams at chunk granularity (FAST's discipline, PAPERS.md).

On this container the transport is :class:`ModeledDCN` — deterministic
latency from the priority wire model plus a seeded fault plan
(:class:`WireFault`): transfer drop (no arrival before the deadline —
the modeled-clock analogue of the live watchdog, the same move
``resilience.simulate`` makes for record-mode traces), corrupt page in
flight, stale/mismatched stamp sidecar, and prefill-slice
``rank_abort`` mid-handoff.  The fault matrix's handoff cells
(``resilience.matrix.run_handoff_matrix``) and ``scripts/tdt_lint.py
--handoff`` drive exactly these classes end to end.
"""

from __future__ import annotations

import dataclasses
import enum
import random

import numpy as np

from .. import obs
from ..comm import dcn
from ..resilience.errors import (
    CollectiveTimeoutError,
    CorruptionDiagnosis,
    PayloadCorruption,
    TimeoutDiagnosis,
)
from .budget import lifecycle_recorder, page_event, pages_needed

HANDOFF_OP = "handoff_transfer"


class HandoffFault(enum.Enum):
    """The handoff threat model (docs/robustness.md): every class the
    fault matrix must show detected-or-survived."""

    TRANSFER_DROP = "transfer_drop"
    CORRUPT_PAGE = "corrupt_page_in_flight"
    STALE_STAMP = "stale_stamp"
    PREFILL_ABORT = "prefill_rank_abort"
    DECODE_SATURATED = "decode_saturated"


HANDOFF_FAULT_KINDS = tuple(HandoffFault)


@dataclasses.dataclass(frozen=True)
class HandoffConfig:
    """Knobs of the transfer ladder.  ``backoff_ms`` defaults to 0: the
    modeled wire resolves congestion in MODEL time, so a wall-clock
    sleep only slows CI; a live deployment sets a real backoff.
    ``wire_dtype``: "auto" consults ``tools.calibrate.codec_pays("dcn")``
    at the page's row width; "raw" ships pool bytes; "int8" forces the
    codec."""

    max_retries: int = 2
    backoff_ms: float = 0.0
    wire_dtype: str = "auto"
    breaker_threshold: int = 3


@dataclasses.dataclass
class PagePayload:
    """One request's finished KV pages on the wire.

    ``wire``: "raw" (pool-dtype bytes), "int8" (per-page int8 rows +
    f32 scale sidecars, ``lang.quant``'s codec), or "pool" (an int8 KV
    pool's pages + per-(page, head) scale sidecars verbatim).
    ``stamps``: logical page -> fold32 over that page's WIRE bytes
    (consumer-verified before implant).  ``cache_stamps``: logical page
    -> fold32 over the producer's POOL bytes (full prompt pages only;
    carried into the re-prefill fallback via ``Request.kv_stamps``).
    """

    req_id: int
    prompt_len: int
    first_token: int
    n_pages: int
    page_shape: tuple        # (L, Hkv, page_size, D) of one pool page
    wire: str                # "raw" | "int8" | "pool"
    k: np.ndarray
    v: np.ndarray
    k_scale: np.ndarray | None
    v_scale: np.ndarray | None
    stamps: dict
    cache_stamps: dict
    payload_bytes: int

    def copy(self) -> "PagePayload":
        return dataclasses.replace(
            self, k=self.k.copy(), v=self.v.copy(),
            k_scale=None if self.k_scale is None else self.k_scale.copy(),
            v_scale=None if self.v_scale is None else self.v_scale.copy(),
            stamps=dict(self.stamps), cache_stamps=dict(self.cache_stamps),
        )


def resolve_wire(wire_dtype: str, cache, row_width: int) -> str:
    """The wire layout for one transfer: an int8 pool ships verbatim
    ("pool"); otherwise "auto" asks the measured DCN codec economics
    (``codec_pays``) whether packing pays at this row width."""
    if cache.quantized:
        return "pool"
    if wire_dtype in ("raw", "bf16"):
        return "raw"
    if wire_dtype == "int8":
        return "int8"
    if wire_dtype != "auto":
        raise ValueError(f"unknown handoff wire_dtype {wire_dtype!r}")
    from ..tools import calibrate

    return "int8" if calibrate.codec_pays("dcn", int(row_width)) else "raw"


def _page_stamps(payload: PagePayload) -> dict:
    """fold32 per logical page over the wire arrays — the producer
    stamp the consumer re-folds on arrival."""
    from ..resilience import integrity

    out = {}
    for j in range(payload.n_pages):
        if payload.wire == "int8":
            parts = [payload.k[j], payload.v[j],
                     payload.k_scale[j], payload.v_scale[j]]
        elif payload.wire == "pool":
            parts = [payload.k[:, j], payload.v[:, j],
                     payload.k_scale[:, j], payload.v_scale[:, j]]
        else:
            parts = [payload.k[:, j], payload.v[:, j]]
        out[j] = integrity.fold32(*parts)
    return out


def extract_payload(cache, pages, req, first_token: int, *,
                    wire_dtype: str = "auto", pool=None) -> PagePayload:
    """Pull a finished prompt's pages out of the producer pool and
    build the wire message (see module docstring).  ``pages`` is the
    slot's physical page list; only the ``pages_needed(prompt_len)``
    prefix carries prompt KV (the +1 decode-growth reservation page is
    not shipped).  ``pool``: the producer :class:`~.budget.PagePool`,
    for page-lifecycle attribution (``analysis.pages``) only."""
    from ..resilience import integrity

    ps = cache.page_size
    plen = int(req.prompt_len)
    n = pages_needed(plen, ps)
    pids = [int(p) for p in pages[:n]]
    if lifecycle_recorder() is not None:
        # lifecycle: the shipped prefix is in flight until the router
        # releases (adopted / re-prefill) or colocates (retain)
        page_event("extract", pids, pool=pool)
    k = np.asarray(cache.k[:, pids])          # (L, n, Hkv, ps, D)
    v = np.asarray(cache.v[:, pids])
    page_shape = (k.shape[0],) + k.shape[2:]
    row_width = int(np.prod(page_shape))
    wire = resolve_wire(wire_dtype, cache, row_width)
    ksc = vsc = None
    if wire == "pool":
        ksc = np.asarray(cache.k_scale[:, pids])      # (L, n, Hkv)
        vsc = np.asarray(cache.v_scale[:, pids])
    elif wire == "int8":
        from ..lang import quant
        import jax.numpy as jnp

        def pack(x):
            rows = jnp.asarray(
                x.transpose(1, 0, 2, 3, 4).reshape(n, row_width))
            q, scale = quant.quantize_rows(rows, "int8")
            return np.asarray(q), np.asarray(scale)

        k, ksc = pack(k)
        v, vsc = pack(v)
    payload_bytes = sum(a.nbytes for a in (k, v, ksc, vsc)
                        if a is not None)
    # cache stamps: POOL bytes of every FULL prompt page, the carry the
    # re-prefill fallback verifies a decode-tier recompute against
    # (partial tail pages keep growing, so only full pages pin)
    cache_stamps = {}
    if integrity.enabled():
        folds = integrity.fold_pages(cache, pids[:plen // ps])
        cache_stamps = {j: folds[pids[j]] for j in range(plen // ps)}
    payload = PagePayload(
        req_id=int(req.req_id), prompt_len=plen,
        first_token=int(first_token), n_pages=n, page_shape=page_shape,
        wire=wire, k=k, v=v, k_scale=ksc, v_scale=vsc, stamps={},
        cache_stamps=cache_stamps, payload_bytes=int(payload_bytes),
    )
    payload.stamps = _page_stamps(payload)
    return payload


def verify_payload(payload: PagePayload) -> CorruptionDiagnosis | None:
    """The consumer-side check: re-fold every page's wire bytes and
    compare with the producer stamps.  Returns a diagnosis NAMING the
    first bad page (or a stamp-count mismatch), None when clean."""
    got = _page_stamps(payload)
    if set(got) != set(payload.stamps):
        return CorruptionDiagnosis(
            op=HANDOFF_OP, kind="payload", sem="dcn_handoff",
            chunk=f"stamps[{sorted(set(payload.stamps) ^ set(got))}]",
            note=f"stamp sidecar lists {sorted(payload.stamps)} but the "
                 f"payload carries pages {sorted(got)} — stale or "
                 f"mismatched sidecar")
    for j in sorted(got):
        if got[j] != payload.stamps[j]:
            return CorruptionDiagnosis(
                op=HANDOFF_OP, kind="payload", sem="dcn_handoff",
                chunk=f"page[{j}]",
                note=f"request {payload.req_id} logical page {j}: wire "
                     f"fold {got[j]:#010x} != producer stamp "
                     f"{payload.stamps[j]:#010x}")
    return None


def implant_payload(cache, pages, payload: PagePayload, *, pool=None):
    """Write an arrived (verified) payload into the consumer pool's
    ``pages`` and return the updated cache — dequantizing or
    requantizing as the TARGET layout demands, so either tier may run
    either KV dtype.  ``pool``: the consumer :class:`~.budget.PagePool`,
    for page-lifecycle attribution (``analysis.pages``) only."""
    import jax.numpy as jnp

    from ..models import kv_cache as kvc

    n = payload.n_pages
    pids = [int(p) for p in pages[:n]]
    if lifecycle_recorder() is not None:
        # lifecycle: wire bytes land in freshly reserved pages; the
        # adopting scheduler marks them verified+sealed after this
        # returns (the plane verified the payload before implanting)
        page_event("implant", pids, pool=pool)
    L, hkv, ps, d = payload.page_shape
    if payload.wire == "pool" and cache.quantized:
        # int8 pool -> int8 pool: pages + sidecars land verbatim
        return dataclasses.replace(
            cache,
            k=cache.k.at[:, pids].set(jnp.asarray(payload.k)),
            v=cache.v.at[:, pids].set(jnp.asarray(payload.v)),
            k_scale=cache.k_scale.at[:, pids].set(
                jnp.asarray(payload.k_scale)),
            v_scale=cache.v_scale.at[:, pids].set(
                jnp.asarray(payload.v_scale)),
        )
    if payload.wire == "pool":
        vals_k = payload.k.astype(np.float32) \
            * payload.k_scale[..., None, None]
        vals_v = payload.v.astype(np.float32) \
            * payload.v_scale[..., None, None]
    elif payload.wire == "int8":
        from ..lang import quant

        def unpack(q, scale):
            rows = quant.dequantize_rows(
                jnp.asarray(q), jnp.asarray(scale), jnp.float32)
            return np.asarray(rows).reshape(n, L, hkv, ps, d) \
                .transpose(1, 0, 2, 3, 4)

        vals_k = unpack(payload.k, payload.k_scale)
        vals_v = unpack(payload.v, payload.v_scale)
    else:
        vals_k, vals_v = payload.k, payload.v
    if cache.quantized:
        qk, sk = kvc._quantize_pages(jnp.asarray(vals_k))
        qv, sv = kvc._quantize_pages(jnp.asarray(vals_v))
        return dataclasses.replace(
            cache,
            k=cache.k.at[:, pids].set(qk),
            v=cache.v.at[:, pids].set(qv),
            k_scale=cache.k_scale.at[:, pids].set(sk),
            v_scale=cache.v_scale.at[:, pids].set(sv),
        )
    return dataclasses.replace(
        cache,
        k=cache.k.at[:, pids].set(
            jnp.asarray(vals_k).astype(cache.k.dtype)),
        v=cache.v.at[:, pids].set(
            jnp.asarray(vals_v).astype(cache.v.dtype)),
    )


# ---------------------------------------------------------------------------
# the modeled transport


@dataclasses.dataclass(frozen=True)
class WireFault:
    """One planned fault on the modeled DCN: ``kind`` hits transfer
    number ``transfer`` (0-based, in plane order) on its first
    ``attempts`` attempts (None = every attempt, which forces the
    transfer all the way down the ladder to re-prefill)."""

    kind: HandoffFault
    transfer: int
    attempts: int | None = None


class ModeledDCN:
    """The SimBackend-tier transport: deterministic latency from the
    priority wire model plus the seeded fault plan.  A dropped (or
    congestion-delayed-past-deadline) transfer raises
    :class:`CollectiveTimeoutError` against the caller's SOL deadline
    on the MODEL clock — the same simulator-world deadline move
    ``resilience.simulate`` makes for recorded traces, because a wall
    sleep past the CPU watchdog floor would take a minute per cell."""

    def __init__(self, *, wire: dcn.PriorityDCNWire | None = None,
                 faults=(), seed: int = 0):
        self.wire = wire if wire is not None else dcn.PriorityDCNWire()
        self.faults = list(faults)
        self.transfers = 0
        self.drops = 0
        self._rng = random.Random(seed)

    def _fault_for(self, idx: int, attempt: int) -> WireFault | None:
        for f in self.faults:
            if f.transfer == idx and (f.attempts is None
                                      or attempt < f.attempts):
                return f
        return None

    def transmit(self, payload: PagePayload, *, deadline_ms: float,
                 priority: int = dcn.LATENCY, attempt: int = 0):
        """One attempt: returns ``(arrived_payload, modeled_ms)`` or
        raises the fault class the plan scheduled."""
        if attempt == 0:
            self.transfers += 1
        idx = self.transfers - 1
        fault = self._fault_for(idx, attempt)
        if fault is not None and fault.kind is HandoffFault.PREFILL_ABORT:
            from ..resilience.faults import RankAborted

            raise RankAborted(0, idx)
        if fault is not None and fault.kind is HandoffFault.TRANSFER_DROP:
            self.drops += 1
            raise CollectiveTimeoutError(
                HANDOFF_OP, deadline_ms, TimeoutDiagnosis(
                    kernel=HANDOFF_OP, ranks=2, static=True,
                    note=f"transfer #{idx} (request {payload.req_id}, "
                         f"{payload.n_pages} page(s), "
                         f"{payload.payload_bytes} B) dropped on the DCN "
                         f"wire: no arrival before the SOL deadline"))
        arrived = payload
        if fault is not None and fault.kind is HandoffFault.CORRUPT_PAGE:
            arrived = payload.copy()
            j = self._rng.randrange(payload.n_pages)
            # flip one byte inside page j's wire region (page-major rows
            # for the int8 codec, pool-page slices otherwise)
            if arrived.wire == "int8":
                row = np.ascontiguousarray(arrived.k[j])
                row.view(np.uint8).reshape(-1)[
                    self._rng.randrange(row.nbytes)] ^= 0xFF
                arrived.k[j] = row
            else:
                pg = np.ascontiguousarray(arrived.k[:, j])
                pg.view(np.uint8).reshape(-1)[
                    self._rng.randrange(pg.nbytes)] ^= 0xFF
                arrived.k[:, j] = pg
        elif fault is not None and fault.kind is HandoffFault.STALE_STAMP:
            arrived = payload.copy()
            arrived.stamps = {j: (s ^ 0x5A17A317) & 0xFFFFFFFF
                              for j, s in arrived.stamps.items()}
        ms = self.wire.send(payload.payload_bytes, priority=priority)
        if deadline_ms is not None and ms > deadline_ms:
            self.drops += 1
            raise CollectiveTimeoutError(
                HANDOFF_OP, deadline_ms, TimeoutDiagnosis(
                    kernel=HANDOFF_OP, ranks=2, static=True,
                    note=f"transfer #{idx}: modeled DCN completion "
                         f"{ms:.1f} ms exceeds the SOL deadline (shared-"
                         f"wire congestion)"))
        return arrived, ms

    def snapshot(self) -> dict:
        return {"transfers": self.transfers, "drops": self.drops,
                "faults_planned": len(self.faults),
                "wire": self.wire.snapshot()}


# ---------------------------------------------------------------------------
# the plane


class HandoffPlane:
    """One handoff channel prefill tier -> decode tier: the transfer
    ladder plus its accounting (the ``serve_handoff_*`` telemetry and
    the fault-matrix evidence)."""

    def __init__(self, *, dcn_channel: ModeledDCN | None = None,
                 config: HandoffConfig | None = None):
        from ..resilience import RetryPolicy

        self.dcn = dcn_channel if dcn_channel is not None else ModeledDCN()
        self.cfg = config or HandoffConfig()
        self._policy = RetryPolicy(
            max_retries=self.cfg.max_retries,
            backoff_ms=self.cfg.backoff_ms,
            breaker_threshold=self.cfg.breaker_threshold,
            retry_on=(CollectiveTimeoutError, PayloadCorruption),
        )
        self.transfers = 0
        self.delivered = 0
        self.retries = 0
        self.exhausted = 0
        self.pages_moved = 0
        self.corruptions: list[dict] = []
        self.handoff_ms: list[float] = []

    def transfer(self, payload: PagePayload,
                 trace=None) -> PagePayload | None:
        """Run one transfer down the ladder.  Returns the VERIFIED
        arrived payload, or None when the ladder bottomed out (retries
        exhausted, or the sticky ``handoff_transfer`` breaker is open)
        — the caller's cue for the terminal fallback, re-prefill on the
        decode tier.  A prefill-slice ``RankAborted`` propagates: there
        is nothing left to retry against.

        ``trace`` (TDT_TRACE=1, ``obs.request_trace``): the request's
        trace context — per-attempt DCN wire time and stamp-verify time
        land as overlay events (the wire/verify split of the handoff
        phase), and the ladder's retry rungs attach their reason
        strings through ``request_trace.activate`` so a faulted
        transfer's trace names every rung it burned."""
        from .. import resilience
        from ..obs import request_trace

        deadline = resilience.deadline_ms(
            HANDOFF_OP, payload_bytes=payload.payload_bytes, num_ranks=2)
        self.transfers += 1
        attempt = {"n": 0}

        def thunk():
            a = attempt["n"]
            attempt["n"] += 1
            if a:
                self.retries += 1
                if obs.enabled():
                    obs.counter("handoff_retries").inc()
            t0 = trace.now_us() if trace is not None else 0.0
            try:
                arrived, ms = self.dcn.transmit(
                    payload, deadline_ms=deadline, priority=dcn.LATENCY,
                    attempt=a)
            except Exception as e:
                if trace is not None:
                    trace.event("handoff_wire", t0, trace.now_us(),
                                tier="wire", attempt=a,
                                error=type(e).__name__)
                raise
            t1 = trace.now_us() if trace is not None else 0.0
            if trace is not None:
                trace.event("handoff_wire", t0, t1, tier="wire",
                            attempt=a, modeled_ms=round(float(ms), 4))
            diag = verify_payload(arrived)
            if trace is not None:
                trace.event("stamp_verify", t1, trace.now_us(),
                            tier="wire", attempt=a,
                            clean=diag is None)
            if diag is not None:
                self.corruptions.append({
                    "req_id": payload.req_id, "chunk": diag.chunk,
                    "note": diag.note, "attempt": a,
                })
                if obs.enabled():
                    obs.counter("handoff_corruptions").inc()
                raise PayloadCorruption(HANDOFF_OP, diag)
            return arrived, ms

        with request_trace.activate(trace):
            result = resilience.resilient_call(
                HANDOFF_OP, thunk, fallback=lambda: None,
                deadline_ms=deadline, policy=self._policy)
        if result is None:
            self.exhausted += 1
            if obs.enabled():
                obs.counter("handoff_exhausted").inc()
            return None
        arrived, ms = result
        self.delivered += 1
        self.pages_moved += arrived.n_pages
        self.handoff_ms.append(float(ms))
        if obs.enabled():
            obs.counter("handoff_transfers").inc()
            obs.serve_stats.STATS.observe_handoff(
                float(ms), pages=arrived.n_pages)
        return arrived

    def snapshot(self) -> dict:
        return {
            "transfers": self.transfers,
            "delivered": self.delivered,
            "retries": self.retries,
            "exhausted": self.exhausted,
            "pages_moved": self.pages_moved,
            "corruptions": len(self.corruptions),
            "dcn": self.dcn.snapshot(),
        }
