"""Overload-safe continuous-batching serving over the paged KV cache.

The layer above the kernels: ROADMAP item 1.  The engine's jitted step
functions stay STATELESS (shapes fixed, values per-step — membership
changes never retrace); everything stateful — the bounded admission
queue, the KV-page free list, chunked prefill, preemption, per-sequence
failure isolation, deadline enforcement, degradation, telemetry — lives
in the Python scheduler loop here.  ``docs/serving.md`` is the
operator-facing spec (policies, env knobs, SLO metric names).

Quick shape::

    from triton_distributed_tpu import serve

    sched = engine.scheduler(pool_pages=4096)   # or serve.Scheduler(
    sched.submit(serve.Request(prompt=ids,      #   serve.SimBackend())
                 max_new_tokens=128, priority=1,
                 deadline_ms=30_000))
    while not sched.step().idle:
        pass
"""

from __future__ import annotations

from ..models.kv_cache import PagePoolExhausted
from .backends import EngineBackend, SimBackend
from .budget import (
    SCRAP_PAGE,
    PageLifecycleError,
    PagePool,
    pages_needed,
    scrub_enabled,
)
from .handoff import (
    HANDOFF_FAULT_KINDS,
    HANDOFF_OP,
    HandoffConfig,
    HandoffFault,
    HandoffPlane,
    ModeledDCN,
    PagePayload,
    WireFault,
    extract_payload,
    implant_payload,
    verify_payload,
)
from .fleet import (
    FLEET_FAULT_KINDS,
    REPLICA_BREAKER_PREFIX,
    FleetConfig,
    FleetFault,
    FleetRouter,
    FleetStepResult,
    Replica,
    replica_breaker_name,
)
from .queue import Request, RequestQueue, RequestState, TERMINAL_STATES
from .router import DisaggRouter, RouterConfig, RouterStepResult
from .scheduler import Scheduler, SchedulerConfig, SlotState, StepResult
from .trace import Arrival, TraceReport, replay, synthetic_trace

__all__ = [
    "Arrival", "DisaggRouter", "EngineBackend", "FLEET_FAULT_KINDS",
    "FleetConfig", "FleetFault", "FleetRouter", "FleetStepResult",
    "HANDOFF_FAULT_KINDS",
    "HANDOFF_OP", "HandoffConfig", "HandoffFault", "HandoffPlane",
    "ModeledDCN", "PageLifecycleError", "PagePayload", "PagePool",
    "PagePoolExhausted", "REPLICA_BREAKER_PREFIX", "Replica",
    "Request", "RequestQueue", "RequestState", "RouterConfig",
    "RouterStepResult", "SCRAP_PAGE", "Scheduler", "SchedulerConfig",
    "SimBackend", "SlotState", "StepResult", "TERMINAL_STATES",
    "TraceReport", "WireFault", "extract_payload", "implant_payload",
    "pages_needed", "replay", "replica_breaker_name", "scrub_enabled",
    "synthetic_trace", "verify_payload",
]
