"""Request lifecycle and the bounded admission queue.

The serving layer's unit of work is a :class:`Request`: a prompt, a
generation budget, a priority, and an optional wall-clock deadline.  The
:class:`RequestQueue` in front of the scheduler is the ADMISSION CONTROL
half of overload safety (Orca's iteration-level scheduling admits from
exactly such a queue, PAPERS.md): depth is bounded, so a traffic burst
beyond the drain rate SHEDS deterministically at submit time (the
client sees backpressure immediately) instead of growing an unbounded
backlog whose tail requests would all miss their deadlines anyway.

States form a small machine::

    QUEUED -> PREFILL -> DECODE -> DONE
      |          \\________/  \\
      v              |         -> FAILED   (fault / deadline, isolated)
     SHED        PREEMPTED -> QUEUED       (pages evicted, deterministic
                                            recompute from the prompt)

Preempted requests re-enter the queue AHEAD of same-priority arrivals
(they already paid admission once; starving them behind fresh traffic
would be priority inversion).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    # prefill finished on a prefill-only tier; the slot holds the pages
    # while the router ships them to the decode tier (serve.router)
    HANDOFF = "handoff"
    PREEMPTED = "preempted"
    DONE = "done"
    FAILED = "failed"
    SHED = "shed"


TERMINAL_STATES = (RequestState.DONE, RequestState.FAILED,
                   RequestState.SHED)

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt``: token ids (any int sequence; stored as a tuple so a
    preempted request can be deterministically recomputed from it).
    ``max_new_tokens``: generation budget.  ``priority``: higher wins
    admission and survives preemption longer.  ``deadline_ms``: wall
    budget from ``submit`` time; breach fails (queued: sheds) the
    request without poisoning batch cohabitants.
    """

    prompt: tuple
    max_new_tokens: int
    priority: int = 0
    deadline_ms: float | None = None
    req_id: int = dataclasses.field(default_factory=lambda: next(_ids))

    # lifecycle (owned by the queue + scheduler)
    state: RequestState = RequestState.QUEUED
    tokens: list = dataclasses.field(default_factory=list)
    error: str | None = None
    shed_reason: str | None = None
    preemptions: int = 0
    submitted_s: float | None = None
    first_token_s: float | None = None
    finished_s: float | None = None
    # checksum-on-evict carry (TDT_INTEGRITY=1, docs/robustness.md "Data
    # integrity"): logical-page -> fold32 stamps of the full prompt
    # pages, taken at preemption and verified when the recompute's
    # prefill completes; None on every path with integrity off
    kv_stamps: dict | None = None
    # per-request trace context (TDT_TRACE=1, obs.request_trace):
    # minted at Scheduler.submit, propagated across every hop — queue,
    # prefill chunks, handoff, adoption, decode windows, preemption —
    # and retired into the trace ring at the terminal state.  Always
    # None with the trace plane off (zero behavior change)
    trace: object | None = None

    def __post_init__(self):
        self.prompt = tuple(int(t) for t in self.prompt)
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens {self.max_new_tokens} < 1")

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def done(self) -> bool:
        return self.state in TERMINAL_STATES

    def remaining_ms(self, now: float | None = None) -> float | None:
        """Wall budget left (None = unbounded); <= 0 means breached."""
        if self.deadline_ms is None or self.submitted_s is None:
            return None
        now = time.monotonic() if now is None else now
        return self.deadline_ms - (now - self.submitted_s) * 1e3

    def ttft_ms(self) -> float | None:
        if self.first_token_s is None or self.submitted_s is None:
            return None
        return (self.first_token_s - self.submitted_s) * 1e3


class RequestQueue:
    """Bounded priority queue with preempted-first re-admission.

    ``submit`` returns False (and marks the request SHED) when the
    queue is at ``max_depth`` — the backpressure contract: a full queue
    is the load balancer's signal to route elsewhere, not a promise to
    buffer forever.  Pop order: priority desc, then preempted before
    fresh, then FIFO by submit order.  Thread-safe (a serving front-end
    submits from request threads; the scheduler pops from its loop).
    """

    def __init__(self, max_depth: int = 64):
        if max_depth < 1:
            raise ValueError(f"max_depth {max_depth} < 1")
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        # (-prio, fresh, seq, Request, enqueued_s) — enqueued_s is THIS
        # residency's entry time (a preempted re-queue restarts it), the
        # clock behind the queued-age high-water mark below
        self._items: list[tuple] = []
        self._seq = itertools.count()
        self.sheds = 0
        self.submitted = 0
        # queued-age high-water per priority class (ISSUE 14 small fix):
        # the depth gauge is a snapshot, so a starving low-priority
        # request is invisible the moment deadline expiry sheds it —
        # this mark keeps the evidence: the LONGEST any request of each
        # priority has sat in the queue, updated on every sweep
        self.age_high_water_s: dict[int, float] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def depth(self) -> int:
        return len(self)

    def submit(self, req: Request, *, now: float | None = None) -> bool:
        """Admit to the queue, or shed (False) when full."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self.submitted += 1
            if len(self._items) >= self.max_depth:
                self.sheds += 1
                req.state = RequestState.SHED
                req.shed_reason = (
                    f"queue full (depth {len(self._items)} >= max_depth "
                    f"{self.max_depth})")
                req.finished_s = now
                return False
            req.submitted_s = now if req.submitted_s is None \
                else req.submitted_s
            req.state = RequestState.QUEUED
            self._items.append((-req.priority, 1, next(self._seq), req,
                                now))
            self._items.sort()
            return True

    def requeue_preempted(self, req: Request) -> None:
        """Park a preempted request: ahead of same-priority fresh
        arrivals, never shed (it already passed admission — dropping it
        now would convert pool pressure into a failed request, exactly
        what preemption exists to avoid).  Its deadline keeps running
        from the ORIGINAL submit."""
        req.state = RequestState.PREEMPTED
        req.preemptions += 1
        req.tokens = []          # deterministic recompute from the prompt
        # first_token_s is KEPT: TTFT is a once-per-request SLO sample
        # from the first admission
        with self._lock:
            self._items.append((-req.priority, 0, next(self._seq), req,
                                time.monotonic()))
            self._items.sort()

    def peek(self) -> Request | None:
        with self._lock:
            return self._items[0][3] if self._items else None

    def pop(self) -> Request | None:
        with self._lock:
            if not self._items:
                return None
            return self._items.pop(0)[3]

    def pop_if(self, req: Request) -> bool:
        """Atomically pop the head IFF it is still ``req`` — the
        admission loop peeks, sizes the page reservation, then commits
        with this; a concurrent submit that changed the head between
        peek and commit makes it return False (the loop re-peeks)
        instead of silently discarding the newcomer."""
        with self._lock:
            if self._items and self._items[0][3] is req:
                self._items.pop(0)
                return True
            return False

    def expire_deadlines(self, now: float | None = None) -> list[Request]:
        """Shed queued requests whose deadline has already passed —
        admitting them would spend pool pages on work that cannot
        finish in budget.  The scheduler sweeps this EAGERLY: on every
        tick AND on every submit, so the depth gauge, the full-queue
        backpressure check, and the saturation-based ``/healthz`` 503
        never count requests that can never run."""
        now = time.monotonic() if now is None else now
        expired = []
        with self._lock:
            keep = []
            for item in self._items:
                req = item[3]
                # the high-water update rides the sweep (every tick AND
                # every submit), so the mark is current BEFORE the
                # expiry below deletes the starving request
                age = now - item[4]
                if age > self.age_high_water_s.get(req.priority, 0.0):
                    self.age_high_water_s[req.priority] = age
                rem = req.remaining_ms(now)
                if rem is not None and rem <= 0:
                    self.sheds += 1
                    req.state = RequestState.SHED
                    req.shed_reason = (
                        f"deadline {req.deadline_ms:.0f} ms expired in "
                        f"queue")
                    req.finished_s = now
                    expired.append(req)
                else:
                    keep.append(item)
            self._items = keep
        return expired

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._items),
                "max_depth": self.max_depth,
                "submitted": self.submitted,
                "sheds": self.sheds,
                "queued_ids": [it[3].req_id for it in self._items],
                # per-priority high-water queued age (seconds): survives
                # the request leaving the queue, so /debug/serve shows a
                # starvation episode even after expiry shed the evidence
                "queued_age_hw_s": {
                    prio: round(age, 6) for prio, age in
                    sorted(self.age_high_water_s.items())
                },
            }
