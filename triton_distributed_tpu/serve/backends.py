"""Execution backends the scheduler drives.

The scheduler (``serve.scheduler``) is pure host logic over a
:class:`~..models.kv_cache.PagedKVCache`: admission, page budgeting,
preemption, isolation.  What actually computes a step is a backend with
two entry points:

- ``prefill_chunk(cache, pages_row, chunk, start, total_len)`` — write
  one prompt chunk's K/V into the pages mapped for one slot; when the
  chunk completes the prompt, also return the first generated token.
- ``decode(cache, tokens)`` — one batched decode step over every slot
  (inactive slots carry the scrap-page row and produce ignored tokens);
  returns the updated cache and the per-slot next token.

Two implementations:

- :class:`EngineBackend` — the real model: jit-compiled STATELESS step
  functions over ``Qwen3.decode`` / ``Qwen3.prefill_chunk`` with the
  cache NOT donated.  Non-donation is deliberate: a failed step must
  leave the pre-step cache intact so cohabitant sequences survive a
  victim's fault (per-sequence failure isolation) — the scheduler pays
  one pool copy per step for recoverability.  Membership changes only
  change block-table/seq-lens VALUES, never shapes, so the step never
  retraces.
- :class:`SimBackend` — a deterministic token automaton over the SAME
  real paged-cache plumbing (``write_chunk_paged`` / ``append_paged``),
  no model, no Pallas, no shard_map: the headless backend the fault
  matrix, ``tdt_lint --serve`` and the CI load tests run on any box.
  K/V values are the token ids themselves, so a test can materialize a
  sequence's pages and assert they hold exactly its token history —
  the strongest cheap evidence that cohabitants were not corrupted.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.kv_cache import (
    PagedKVCache,
    advance,
    append_paged,
    init_serving_cache,
    write_chunk_paged,
)


def _slot_view(cache: PagedKVCache, pages_row: np.ndarray,
               length: int) -> PagedKVCache:
    """A batch-1 view of one slot over the SHARED pools: the slot's own
    block-table row (host truth — the device table may be pointing
    non-decode slots at the scrap page) and its current length."""
    mp = cache.max_pages
    row = np.zeros((1, mp), np.int32)
    row[0, :len(pages_row)] = pages_row
    return dataclasses.replace(
        cache,
        block_table=jnp.asarray(row),
        seq_lens=jnp.asarray([length], jnp.int32),
    )


def _merge_pools(cache: PagedKVCache, view: PagedKVCache) -> PagedKVCache:
    """Adopt the pools a slot view updated (scale sidecars included for
    a quantized cache); table/lens stay the scheduler's."""
    return dataclasses.replace(cache, k=view.k, v=view.v,
                               k_scale=view.k_scale, v_scale=view.v_scale)


class SimBackend:
    """Deterministic serving automaton over a real paged cache.

    Token rule: the next token is a fixed hash of (input token, new
    length) — a function of the prompt alone by induction, so a
    preempted request deterministically recomputes the SAME tokens from
    its prompt, which is exactly the recovery contract the scheduler
    promises.  K/V writes carry the input token's value into every
    (layer, head, dim) slot of its position.

    ``step_hook(step_index)``: called at the top of every decode
    dispatch — the fault matrix's injection point (raise
    ``RankAborted`` to simulate a dead rank mid-step, ``time.sleep`` to
    straggle past a deadline).
    """

    def __init__(self, *, slots: int = 4, page_size: int = 4,
                 pool_pages: int = 32, max_length: int = 64,
                 num_layers: int = 1, kv_heads: int = 1, head_dim: int = 8,
                 vocab: int = 101, step_hook=None, kv_dtype=None,
                 steps_per_dispatch: int = 1):
        from ..core import mesh as mesh_lib
        from ..core.mesh import TP_AXIS, make_mesh

        self.slots = int(slots)
        self.page_size = int(page_size)
        self.pool_pages = int(pool_pages)
        self.max_length = int(max_length)
        self.num_layers = int(num_layers)
        self.kv_heads = int(kv_heads)
        self.head_dim = int(head_dim)
        self.vocab = int(vocab)
        self.step_hook = step_hook
        # kv_dtype="int8": the quantized page layout — the SAME real
        # paged-cache plumbing (dequant-merge-requant writes, scale
        # sidecars), headlessly; tests materialize pages via
        # kv_cache.layer_pool and still see the token history
        self.kv_dtype = kv_dtype
        # steps_per_dispatch: the multi-step window knob the scheduler
        # reads (docs/serving.md) — the automaton's decode_multi loops
        # its one-step rule, calling step_hook per INNER step so fault
        # cells can land mid-window
        self.steps_per_dispatch = max(int(steps_per_dispatch), 1)
        self._mesh = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
        self._step = 0
        del mesh_lib

    def make_cache(self) -> PagedKVCache:
        return init_serving_cache(
            self._mesh, self.num_layers, self.slots, self.kv_heads,
            self.max_length, self.head_dim, jnp.float32,
            page_size=self.page_size, pool_pages=self.pool_pages,
            kv_dtype=self.kv_dtype,
        )

    def next_token(self, tok: int, new_len: int) -> int:
        """The deterministic generation rule (public: tests replay it)."""
        return (int(tok) * 31 + int(new_len) * 7 + 13) % self.vocab

    def expected_tokens(self, req) -> list[int]:
        """Replay the rule from the prompt alone — the ONE golden both
        the fault-matrix cells and the acceptance tests judge recovery
        and cohabitant integrity against."""
        toks = [self.next_token(req.prompt[-1], req.prompt_len)]
        length = req.prompt_len
        while len(toks) < req.max_new_tokens:
            length += 1
            toks.append(self.next_token(toks[-1], length))
        return toks

    def prefill_chunk(self, cache: PagedKVCache, pages_row, chunk,
                      start: int, total_len: int):
        chunk = np.asarray(chunk, np.int32)
        # compute-category span (ISSUE 14 satellite): serve dispatches
        # land in the same process Chrome trace as the comm spans, so
        # the overlap report and the request traces share one timeline
        with obs.span("sim_prefill_chunk", "compute", tokens=len(chunk)):
            view = _slot_view(cache, pages_row, start)
            vals = jnp.broadcast_to(
                jnp.asarray(chunk, jnp.float32)[None, None, :, None],
                (1, self.kv_heads, len(chunk), self.head_dim),
            )
            for layer in range(self.num_layers):
                view = write_chunk_paged(view, layer, vals, vals, start)
            cache = _merge_pools(cache, view)
        first = None
        if start + len(chunk) == total_len:
            first = self.next_token(int(chunk[-1]), total_len)
        return cache, first

    def decode(self, cache: PagedKVCache, tokens):
        # counter moves BEFORE the hook: a raising hook must not pin the
        # step index and re-fire on the retry dispatch
        step = self._step
        self._step += 1
        if self.step_hook is not None:
            self.step_hook(step)
        tokens = np.asarray(tokens, np.int32)
        with obs.span("sim_decode", "compute", step=step):
            tok = jnp.asarray(tokens)
            vals = jnp.broadcast_to(
                tok.astype(jnp.float32)[:, None, None],
                (self.slots, self.kv_heads, self.head_dim),
            )
            for layer in range(self.num_layers):
                cache = append_paged(cache, layer, vals, vals)
            cache = advance(cache, 1)
        new_lens = np.asarray(cache.seq_lens)
        nxt = np.asarray(
            [self.next_token(t, int(l)) for t, l in zip(tokens, new_lens)],
            np.int32,
        )
        return cache, nxt

    def decode_multi(self, cache: PagedKVCache, tokens, steps: int):
        """``steps`` decode steps as ONE dispatch (the scheduler's
        membership-stable window): functional like :meth:`decode` — the
        caller's cache is untouched until the whole window returns, so a
        fault at any inner step discards the window (the non-donated
        isolation contract).  Returns ``(cache, (steps, slots) tokens)``.
        """
        toks = []
        tok = np.asarray(tokens, np.int32)
        for _ in range(int(steps)):
            cache, tok = self.decode(cache, tok)
            toks.append(tok)
        return cache, np.stack(toks)


class EngineBackend:
    """The real-model backend: stateless jitted step functions from the
    engine's Qwen3 model, non-donated (see module docstring), one
    executable per (chunk bucket) + one decode executable — membership
    changes never retrace.

    ``chunk_tokens`` fixes the prefill chunk bucket: every chunk is
    right-padded to it and masked via ``true_len`` (the same
    pad-and-mask contract ``Engine.precompile`` uses for prompt
    buckets), so chunked prefill compiles exactly ONE executable.
    Sampling is greedy — the deterministic-recompute contract
    preemption relies on.

    The engine's ``decode_mode`` (including the ``"fused"`` decode
    megakernel, ``ops.fused_decode``) flows through unchanged: the
    scheduler drives the same stateless ``Qwen3.decode`` signature
    whichever kernel chain implements it, so flipping an engine to
    ``decode_mode="fused"`` swaps the whole serving decode hot path
    without touching scheduler state (``decode_mode`` property below
    surfaces the active mode for health/debug endpoints).
    """

    def __init__(self, engine, *, pool_pages: int | None = None,
                 chunk_tokens: int = 64, steps_per_dispatch: int = 1):
        if engine.cache_layout != "paged":
            raise ValueError(
                "EngineBackend needs cache_layout='paged'; this engine "
                f"has {engine.cache_layout!r}")
        c = engine.model.config
        if c.is_moe:
            raise NotImplementedError(
                "chunked prefill supports the dense MLP path; MoE "
                "serving prefills whole prompts through Engine.serve")
        self.engine = engine
        self.model = engine.model
        self.slots = int(engine.batch)
        self.page_size = int(engine.page_size)
        self.max_length = int(c.max_length)
        self.num_layers = int(c.num_layers)
        self.vocab = int(c.vocab)
        self.chunk_tokens = int(chunk_tokens)
        mp = self.max_length // self.page_size
        self.pool_pages = int(pool_pages) if pool_pages is not None \
            else self.slots * mp + 1
        # steps_per_dispatch (ISSUE 13, docs/serving.md): the scheduler
        # batches membership-STABLE windows of up to this many decode
        # steps into one dispatch of `decode_multi` — the whole window
        # (argmax feedback included) runs on device under one launch,
        # trading per-token host turnarounds against membership
        # staleness of at most steps_per_dispatch - 1 steps
        self.steps_per_dispatch = max(int(steps_per_dispatch), 1)
        # autotuner-hoist (ISSUE 13 satellite): resolve the persistent
        # kernel's tile config ONCE here — the shape key is constant
        # across membership windows (membership edits change VALUES,
        # never shapes), so the hot loop never consults the winner
        # cache; a bench/warmup crown planted before construction (or
        # `tune.fresh_tune_persistent_decode`) is picked up here
        self._persistent_cfg = self._resolve_persistent_config()
        # persistent mode: stack the per-layer weights ONCE here and
        # thread the stack as a jit ARGUMENT — stacking inside the
        # traced bundle would re-materialize the full weight set on
        # every dispatch (a whole-model HBM copy per token window).
        # Weights are immutable for the backend's lifetime; rebuild the
        # backend after a weight swap, like the step executables.
        self._stacked = None
        if getattr(self.model, "decode_mode", None) == "persistent":
            from ..models.qwen import stack_decode_params

            self._stacked = stack_decode_params(engine.params)
        # stateless, NON-donated step executables (models/engine.py
        # refactor): values of table/lens/tokens change per step, shapes
        # never do — one trace each for the scheduler's whole lifetime
        self._decode = jax.jit(self.model.decode)
        self._prefill_chunk = jax.jit(self.model.prefill_chunk)
        # one multi-step executable per steps bucket (steps is static);
        # decode_multi fills this lazily, precompile_decode eagerly
        cfg_hoisted = self._persistent_cfg
        self._decode_multi = jax.jit(
            lambda p, sp, c, t, s: self.model.decode_multi(
                p, c, t, s, persistent_config=cfg_hoisted, stacked=sp),
            static_argnums=(4,))
        # AOT bucket set (precompile_decode / load_precompiled_decode):
        # {steps: Compiled} — serving never retraces mid-traffic
        self._decode_exec: dict[int, object] = {}

    @property
    def decode_mode(self) -> str:
        """The decode kernel chain this backend's step executes
        (``"psum"`` | ``"ar"`` | ``"gemm_ar"`` | ``"fused"`` |
        ``"persistent"``)."""
        return self.model.decode_mode

    def make_cache(self) -> PagedKVCache:
        c = self.model.config
        return init_serving_cache(
            self.model.mesh, c.num_layers, self.slots, c.num_kv_heads,
            c.max_length, c.head_dim, c.dtype, self.model.axis,
            page_size=self.page_size, pool_pages=self.pool_pages,
            kv_dtype=getattr(self.engine, "kv_dtype", None),
        )

    def prefill_chunk(self, cache: PagedKVCache, pages_row, chunk,
                      start: int, total_len: int):
        chunk = np.asarray(chunk, np.int32)
        true = len(chunk)
        pad = self.chunk_tokens - true
        if pad < 0:
            raise ValueError(
                f"chunk of {true} tokens exceeds chunk_tokens="
                f"{self.chunk_tokens}")
        ids = jnp.asarray(
            np.pad(chunk, (0, pad))[None, :], jnp.int32)
        view = _slot_view(cache, pages_row, start)
        with obs.span("prefill_chunk", "compute", tokens=true):
            logits, view = self._prefill_chunk(
                self.engine.params, view, ids, jnp.int32(start),
                jnp.int32(true))
        cache = _merge_pools(cache, view)
        first = None
        if start + true == total_len:
            first = int(jnp.argmax(logits[0, true - 1]))
        return cache, first

    def decode(self, cache: PagedKVCache, tokens):
        if self._stacked is not None:
            # persistent mode: a single step is a steps=1 bundle, so it
            # rides the hoisted weight stack (re-stacking inside the
            # jitted Qwen3.decode would re-materialize the full weight
            # set per dispatch) and the same argmax-greedy semantics
            cache, toks = self.decode_multi(cache, tokens, 1)
            return cache, toks[0]
        tok = jnp.asarray(np.asarray(tokens, np.int32))
        logits, cache = self._decode(self.engine.params, cache, tok)
        return cache, np.asarray(jnp.argmax(logits, axis=-1), np.int32)

    def decode_multi(self, cache: PagedKVCache, tokens, steps: int):
        """``steps`` greedy decode steps in ONE dispatch
        (``Qwen3.decode_multi``): the scheduler's membership-stable
        window.  Non-donated like :meth:`decode` — a fault anywhere in
        the window leaves the pre-window cache intact.  Returns
        ``(cache, (steps, slots) tokens)``; prefers an AOT bucket
        executable (:meth:`precompile_decode`) when one matches."""
        steps = int(steps)
        tok = jnp.asarray(np.asarray(tokens, np.int32))
        ex = self._decode_exec.get(steps)
        with obs.span("decode_multi", "compute", steps=steps):
            if ex is not None:
                toks, cache = self.engine._call_exec(
                    ex, self.engine.params, self._stacked, cache, tok)
            else:
                toks, cache = self._decode_multi(
                    self.engine.params, self._stacked, cache, tok, steps)
        return cache, np.asarray(toks, np.int32)

    def _resolve_persistent_config(self):
        """The ISSUE-13 autotuner hoist: the persistent kernel's tile
        config, resolved ONCE at backend construction from the winner
        cache (shape key is membership-invariant) and threaded
        explicitly through every ``decode_multi`` trace — no winner-
        cache consult ever runs inside the serving hot loop.  None for
        non-persistent modes and degenerate meshes."""
        if getattr(self.model, "decode_mode", None) != "persistent":
            return None
        n = self.model.tp
        if n < 2:
            return None   # the n==1 path is the pure-XLA reference
        from ..ops import persistent_decode as pd
        from ..tune import autotuner as tune

        c = self.model.config
        key = pd.persistent_config_key(
            c.num_layers, self.slots, c.hidden, c.intermediate,
            c.num_kv_heads, self.page_size,
            self.max_length // self.page_size, c.head_dim, n,
            jnp.dtype(c.dtype))
        # tracing=True == pure cache consult: a cached crown (bench
        # warmup, fresh_tune_persistent_decode) is adopted, otherwise
        # the default — never a measurement at construction time
        return tune.resolve_config(
            "persistent_decode", key,
            # the SHARED pruned sweep — all three persistent resolve
            # paths must hand resolve_config the identical list (the
            # candidates digest keys the winner cache)
            pd.persistent_candidates_pruned(
                c.num_layers, self.slots, c.hidden, c.intermediate,
                c.num_heads, c.num_kv_heads, self.page_size, c.head_dim,
                n, jnp.dtype(c.dtype)),
            pd.PersistentDecodeConfig(),
            lambda cfg: (lambda: None),
            tracing=True,
        )

    # -- AOT bucket set (ISSUE 13 satellite) ------------------------------

    _MANIFEST = "aot_decode_manifest.json"

    def precompile_decode(self, steps_buckets=(),
                          save_dir: str | None = None) -> dict:
        """AOT-compile the serving decode grid — (batch = the backend's
        slot count) x (every steps bucket, ``steps_per_dispatch`` and 1
        always included) — so serving never retraces mid-traffic; the
        manifest rides the PR-2 ``arch``-fingerprinted path
        (``models.engine.arch_fingerprint`` / ``check_arch``), so a
        bundle compiled for a different model, mesh, pool geometry or
        decode mode fails loudly at load."""
        import json
        import os

        from ..core import platform
        from ..models.engine import arch_fingerprint
        from ..tools import aot

        buckets = sorted({1, self.steps_per_dispatch}
                         | {int(s) for s in steps_buckets})
        if buckets[0] < 1:
            raise ValueError(f"steps buckets must be >= 1; got {buckets}")
        cache0 = self.make_cache()
        tok = jnp.zeros((self.slots,), jnp.int32)
        for s in buckets:
            self._decode_exec[s] = self._decode_multi.lower(
                self.engine.params, self._stacked, cache0, tok,
                s).compile()
        manifest = {
            "steps_buckets": buckets,
            "batch": self.slots,
            "page_size": self.page_size,
            "pool_pages": self.pool_pages,
            "chunk_tokens": self.chunk_tokens,
            "decode_mode": self.model.decode_mode,
            "kv_dtype": getattr(self.engine, "kv_dtype", None),
            "arch": arch_fingerprint(self.model.config, self.model.mesh,
                                     self.model.axis),
        }
        if save_dir is not None:
            if platform.on_cpu():
                # same contract as Engine.precompile, probed via the
                # platform (interpret_mode() needs InterpretParams,
                # absent on older jax builds): interpret kernels embed
                # python callbacks XLA cannot serialize
                raise RuntimeError(
                    "serializing AOT bundles requires real-TPU lowering "
                    "(interpret kernels embed python callbacks XLA "
                    "cannot serialize)")
            os.makedirs(save_dir, exist_ok=True)
            for s, ex in self._decode_exec.items():
                aot.save(ex, os.path.join(save_dir, f"decode_multi_{s}.xla"))
            with open(os.path.join(save_dir, self._MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
        return manifest

    def load_precompiled_decode(self, save_dir: str) -> dict:
        """Restore :meth:`precompile_decode`'s executables in another
        process: after this, every windowed decode dispatch within the
        bucket set runs with zero tracing."""
        import json
        import os

        from ..models.engine import arch_fingerprint, check_arch
        from ..tools import aot

        with open(os.path.join(save_dir, self._MANIFEST)) as f:
            manifest = json.load(f)
        mine = {
            "batch": self.slots,
            "page_size": self.page_size,
            "pool_pages": self.pool_pages,
            "chunk_tokens": self.chunk_tokens,
            "decode_mode": self.model.decode_mode,
            "kv_dtype": getattr(self.engine, "kv_dtype", None),
        }
        for field, have in mine.items():
            want = manifest.get(field)
            if want != have:
                raise ValueError(
                    f"AOT decode bundle was compiled for {field}="
                    f"{want!r}; this backend has {field}={have!r}")
        check_arch(manifest,
                   arch_fingerprint(self.model.config, self.model.mesh,
                                    self.model.axis))
        self._decode_exec = {
            int(s): aot.load(os.path.join(save_dir, f"decode_multi_{s}.xla"))
            for s in manifest["steps_buckets"]
        }
        return manifest
