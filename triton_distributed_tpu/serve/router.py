"""The two-tier disaggregated serving router (ROADMAP item 5).

Production engines split serving across slices by PHASE: a
prefill-specialized tier runs chunked prefill (compute-bound, long
kernels) and ships finished KV pages to a decode-specialized tier
(memory-bound, latency-critical steps), so a long prompt never steals
decode step time and each pool is sized for its phase.
:class:`DisaggRouter` owns one :class:`~.scheduler.Scheduler` per tier
— the prefill tier runs with ``SchedulerConfig.prefill_only=True`` and
parks finished prompts in HANDOFF state — plus the fault-tolerant
transfer plane (``serve.handoff``).  Per router ``step()``:

1. the prefill tier steps (admission, chunked prefill);
2. parked handoffs pump through the plane's ladder:
   - decode tier saturated (``adopt_prefilled`` refuses under its OWN
     admission policy) -> **colocate**: the request finishes decode on
     the prefill tier, where its pages already live;
   - transfer verified and adopted -> prefill pages released;
   - ladder bottom (drop/corruption retries exhausted, open breaker)
     or a prefill-slice ``RankAborted`` mid-handoff -> **re-prefill**:
     the request re-queues on the decode tier and recomputes from its
     prompt, with the producer's page stamps carried on
     ``Request.kv_stamps`` so the recompute is verified like a
     preemption restore;
3. the decode tier steps (adopted membership decodes, re-prefills run
   through its normal prefill path).

Routing is TELEMETRY-DRIVEN, the PR-5 plane as the load-balancing
signal: ``submit`` reads each tier's queue-depth and pool-occupancy
gauges (the exact values ``/metrics`` publishes) and a pressured
prefill tier with a healthy decode tier routes the request COLOCATED to
the decode tier; ``health()`` aggregates both tiers — ``/healthz``
answers 503 while EITHER tier is saturated or any breaker is open, and
flips back to 200 as each drains independently (pinned by
``tests/test_obs.py``'s two-tier endpoint battery).
"""

from __future__ import annotations

import dataclasses

from .. import obs
from . import handoff as handoff_mod
from .queue import Request, RequestState
from .scheduler import Scheduler, StepResult


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router knobs.  ``queue_pressure``: the queue-depth fraction at
    which a tier counts as pressured for submit routing;
    ``pool_pressure``: same for pool occupancy.  ``bulk_bytes_per_step``
    models bulk prefill/collective streams sharing the DCN wire (the
    traffic handoff transfers must preempt — ``bench.py serve_disagg``
    exercises it); ``step_wall_ms`` advances the modeled wire clock per
    router step."""

    max_transfers_per_step: int = 4
    queue_pressure: float = 0.75
    pool_pressure: float = 0.95
    colocate_on_saturation: bool = True
    # router steps a parked handoff waits for the decode tier before
    # the saturation shed: a decode tier that is merely BUSY (slots
    # cycling) clears within a step or two, while genuine saturation
    # persists — colocating on the first refusal would convert every
    # transient busy moment into a colocated request
    adopt_patience_steps: int = 2
    bulk_bytes_per_step: int = 0
    step_wall_ms: float = 1.0


@dataclasses.dataclass
class RouterStepResult:
    prefill: StepResult
    decode: StepResult
    handoffs: int = 0
    colocated: int = 0
    reprefills: int = 0

    @property
    def idle(self) -> bool:
        return self.prefill.idle and self.decode.idle


class DisaggRouter:
    """Two schedulers + one handoff plane (see module docstring).
    Single-threaded like the schedulers it drives; ``submit`` is as
    thread-safe as theirs."""

    def __init__(self, prefill: Scheduler, decode: Scheduler, *,
                 plane: handoff_mod.HandoffPlane | None = None,
                 config: RouterConfig | None = None):
        if not prefill.cfg.prefill_only:
            raise ValueError(
                "the prefill tier's SchedulerConfig must set "
                "prefill_only=True — without it finished prompts enter "
                "decode locally and nothing ever hands off")
        # page GEOMETRY must match for an implant to land (pool dtypes
        # MAY differ — implant_payload dequantizes/requantizes per the
        # target layout); fail fast here instead of crashing the first
        # _pump_handoffs with a raw shape error
        pk, dk = prefill.cache.k, decode.cache.k
        if (pk.shape[0], pk.shape[2:]) != (dk.shape[0], dk.shape[2:]):
            raise ValueError(
                f"tier page geometries differ — prefill pages are "
                f"(layers={pk.shape[0]}, kv_heads={pk.shape[2]}, "
                f"page_size={pk.shape[3]}, head_dim={pk.shape[4]}) but "
                f"decode pages are (layers={dk.shape[0]}, "
                f"kv_heads={dk.shape[2]}, page_size={dk.shape[3]}, "
                f"head_dim={dk.shape[4]}); a handoff payload cannot be "
                f"implanted across different page shapes (pool SIZES "
                f"and kv dtypes may differ freely)")
        self.prefill = prefill
        self.decode = decode
        # request traces name the tier each hop ran on (TDT_TRACE=1)
        prefill.trace_tier = "prefill"
        decode.trace_tier = "decode"
        # the re-prefill stamp carry (fold32 over the producer's POOL
        # bytes) only pins a recompute on a tier with the SAME pool
        # layout: a decode tier storing int8 where the prefill tier
        # stored f32 recomputes byte-DIFFERENT (correct) pages, and
        # carrying the stamps would fail every re-prefill with a
        # spurious PayloadCorruption
        self._stamp_carry_ok = (
            pk.dtype == dk.dtype
            and prefill.cache.quantized == decode.cache.quantized)
        self.plane = plane if plane is not None else handoff_mod.HandoffPlane()
        self.cfg = config or RouterConfig()
        self.handoffs = 0
        self.colocated = 0
        self.reprefills = 0
        self.aborts = 0
        self.reprefill_ids: set[int] = set()
        self._park_strikes: dict[int, int] = {}

    # -- routing -----------------------------------------------------------

    def _pressured(self, sched: Scheduler) -> bool:
        """The load-balancing signal: the SAME queue-depth and
        pool-occupancy values the tier's gauges publish, plus its
        saturation latch."""
        if sched._saturated_since is not None:
            return True
        q = sched.queue.depth / sched.queue.max_depth
        return (q >= self.cfg.queue_pressure
                or sched.pool.occupancy() >= self.cfg.pool_pressure)

    def submit(self, req: Request, *, now: float | None = None) -> bool:
        """Admission: the prefill tier is the default entry; a
        pressured prefill tier with a healthy decode tier routes the
        request COLOCATED to the decode tier (it prefills and decodes
        there).  Both pressured -> normal shed semantics on the prefill
        tier."""
        if self._pressured(self.prefill) and not self._pressured(self.decode):
            if obs.enabled():
                obs.counter("router_colocated_submits").inc()
            return self.decode.submit(req, now=now)
        return self.prefill.submit(req, now=now)

    # -- the step ----------------------------------------------------------

    def step(self) -> RouterStepResult:
        h0, c0, r0 = self.handoffs, self.colocated, self.reprefills
        rp = self.prefill.step()
        self._pump_handoffs()
        # continuous profiler (TDT_PROFILE=1, ISSUE 16): the pump's DCN
        # handoff traffic drains under the "handoff" tier before the
        # decode tick claims the rest of the ring for "decode"
        obs.continuous.on_step("handoff", self.prefill.steps)
        rd = self.decode.step()
        # advance the modeled wire clock (bulk backlogs drain; a real
        # transport ignores this)
        wire = getattr(self.plane.dcn, "wire", None)
        if wire is not None:
            wire.tick(self.cfg.step_wall_ms)
        return RouterStepResult(
            prefill=rp, decode=rd,
            handoffs=self.handoffs - h0,
            colocated=self.colocated - c0,
            reprefills=self.reprefills - r0,
        )

    def run_until_idle(self, *, max_steps: int = 100_000) -> int:
        for _ in range(max_steps):
            if self.step().idle:
                return self.prefill.steps
        raise RuntimeError(
            f"router not idle after {max_steps} steps: "
            f"{self.debug_state()}")

    def _pump_handoffs(self) -> None:
        # the pump runs under a process-level span (ISSUE 14 satellite)
        # so the router shares the scheduler ticks' Chrome timeline
        with obs.span("router_pump", "step"):
            self._pump_handoffs_impl()

    def _pump_handoffs_impl(self) -> None:
        from ..comm import dcn
        from ..resilience.faults import RankAborted

        if self.cfg.bulk_bytes_per_step:
            # the bulk prefill/collective streams sharing the wire —
            # the traffic the LATENCY-class handoff sends preempt
            wire = getattr(self.plane.dcn, "wire", None)
            if wire is not None:
                wire.send(self.cfg.bulk_bytes_per_step,
                          priority=dcn.BULK)
        for i in self.prefill.handoff_ready()[
                :self.cfg.max_transfers_per_step]:
            slot = self.prefill.slots[i]
            req = slot.request
            if not self.decode.can_adopt(req):
                # decode tier cannot take it: wait out a transient busy
                # spell, then shed back to colocated mode BEFORE paying
                # the wire (the pages never left this tier's pool)
                strikes = self._park_strikes.get(req.req_id, 0) + 1
                self._park_strikes[req.req_id] = strikes
                if self.cfg.colocate_on_saturation and \
                        strikes > self.cfg.adopt_patience_steps:
                    self._park_strikes.pop(req.req_id, None)
                    self.prefill.colocate(i)
                    self.colocated += 1
                continue
            self._park_strikes.pop(req.req_id, None)
            tr = req.trace
            if tr is not None:
                tr.begin("handoff_extract", tier=self.prefill.trace_tier)
            payload = handoff_mod.extract_payload(
                self.prefill.cache, slot.pages, req, slot.next_token,
                wire_dtype=self.plane.cfg.wire_dtype,
                pool=self.prefill.pool)
            if tr is not None:
                tr.begin("handoff_transfer", tier=self.prefill.trace_tier,
                         pages=payload.n_pages,
                         bytes=payload.payload_bytes, wire=payload.wire)
            try:
                arrived = self.plane.transfer(payload, trace=tr)
            except RankAborted as e:
                # the prefill slice died mid-handoff: nothing to retry
                # against — the decode tier recomputes from the prompt
                self.aborts += 1
                if obs.enabled():
                    obs.counter("handoff_aborts").inc()
                self._reprefill(i, req, payload,
                                reason=f"prefill slice aborted "
                                       f"mid-handoff ({e})")
                continue
            if arrived is None:
                self._reprefill(i, req, payload,
                                reason="transfer ladder exhausted")
                continue
            adopted = self.decode.adopt_prefilled(
                req,
                lambda cache, pages: handoff_mod.implant_payload(
                    cache, pages, arrived, pool=self.decode.pool),
                length=arrived.prompt_len,
                next_token=arrived.first_token)
            if adopted:
                self.prefill.release_handoff(i)
                self.handoffs += 1
            elif self.cfg.colocate_on_saturation:
                # decode tier saturated: shed back to colocated mode —
                # the pages never left this tier's pool
                self.prefill.colocate(i)
                self.colocated += 1
            # else: stay parked; retried next step

    def _reprefill(self, i: int, req: Request,
                   payload: handoff_mod.PagePayload, *,
                   reason: str) -> None:
        """The terminal fallback: recompute the prompt on the decode
        tier, verified against the producer's page stamps exactly like
        a preemption restore (``Scheduler._verify_restore``)."""
        from ..resilience import integrity
        from .budget import pages_needed

        total = req.prompt_len + req.max_new_tokens
        if (self.decode.queue.depth >= self.decode.queue.max_depth
                or pages_needed(total, self.decode.pool.page_size)
                > self.decode.pool.capacity
                or total > self.decode.backend.max_length):
            # no queue room (or a demand that tier can never hold) for
            # the recompute: colocating loses nothing — the pages are
            # still here — and sheds no work
            self.prefill.colocate(i)
            self.colocated += 1
            return
        req.tokens = []
        if integrity.enabled() and payload.cache_stamps \
                and self._stamp_carry_ok and req.kv_stamps is None:
            req.kv_stamps = dict(payload.cache_stamps)
        self.prefill.release_handoff(i)
        self.reprefills += 1
        self.reprefill_ids.add(req.req_id)
        if req.trace is not None:
            # the terminal-fallback rung, named: the decode.submit below
            # re-enters the queue phase on the SAME chain
            req.trace.annotate("reprefill", tier=self.decode.trace_tier,
                               reason=reason)
        if obs.enabled():
            obs.counter("handoff_reprefills").inc()
        if not self.decode.submit(req):
            # the submit-time demand checks shed it (terminal state,
            # accounted on the decode tier) — pages already released,
            # nothing leaks
            if obs.enabled():
                obs.counter("handoff_reprefill_shed").inc()

    # -- health / introspection --------------------------------------------

    def health(self) -> dict:
        """The tier-aggregated ``/healthz`` payload: the process
        resilience snapshot (breakers — an open one already flips
        status to "degraded"), live serve stats, both tiers' state, and
        saturation aggregation: 503 while EITHER tier is saturated,
        back to 200 as each drains."""
        from .. import resilience

        snap = resilience.health_snapshot()
        snap["serve_stats"] = obs.serve_stats.STATS.snapshot()
        snap["tiers"] = {
            "prefill": self.prefill.debug_state(),
            "decode": self.decode.debug_state(),
        }
        snap["handoff"] = self.snapshot()
        saturated = [
            name for name, sched in (("prefill", self.prefill),
                                     ("decode", self.decode))
            if sched._saturated_since is not None
            and sched.saturated_s() >= sched.cfg.saturation_sustain_s
        ]
        snap["saturated_tiers"] = saturated
        if snap["status"] == "ok" and saturated:
            snap["status"] = "saturated"
        return snap

    def snapshot(self) -> dict:
        return {
            "handoffs": self.handoffs,
            "colocated": self.colocated,
            "reprefills": self.reprefills,
            "aborts": self.aborts,
            "plane": self.plane.snapshot(),
        }

    def debug_state(self) -> dict:
        return {
            "handoff": self.snapshot(),
            "tiers": {
                "prefill": self.prefill.debug_state(),
                "decode": self.decode.debug_state(),
            },
        }

    def leaked_pages(self) -> int:
        """Used pages across BOTH tiers once everything drained — the
        zero-leak invariant ``tdt_lint --handoff`` gates on."""
        return self.prefill.pool.used_pages + self.decode.pool.used_pages
