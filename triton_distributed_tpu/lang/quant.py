"""Shared low-precision wire codecs: the quantize/pack machinery every
quantized payload in the framework rides.

Promoted out of ``ops/moe_utils.py`` / ``layers/moe.py`` (ISSUE 9): the
fp8 A2A payload codec the MoE layer prototyped — e4m3 payload + f32
scale sidecar in ONE uint8 wire message (the reference's production
low-latency A2A configuration, ``low_latency_all_to_all.py:36-120``) —
generalized to a registry of wire dtypes and shared by:

- the quantized collective entries (``comm.quantized`` — AG/RS/AR/A2A
  with ``wire_dtype``), which pack at the producer, ship u8, and
  dequantize at the consumer;
- the MoE EP wire (``layers.moe``), which keeps its straight-through
  custom-vjp transports but consumes THIS codec;
- the int8 KV-cache layout (``models.kv_cache``), which uses the same
  per-row quantization math at (page, head) granularity;
- the integrity plane (``resilience.integrity``), whose quantized
  verifiers re-run :func:`reduce_roundtrip` as the golden.

Wire message layout (identical for every quantized dtype, so one unpack
serves all): ``(..., H + SIDECAR)`` uint8 — H payload bytes (the
quantized row, bitcast to u8) followed by a ``SIDECAR``-lane block whose
first 4 bytes carry the row's f32 scale little-endian (the remaining
lanes are zero padding that keeps the message lane-aligned for DMA).
One byte per element + the sidecar ≈ halves the wire bytes of a bf16
payload at serving widths (H >= 1024).

Error envelopes (relative to the ROW absmax — the bound the property
tests pin and the parity gates scale their tolerances from):

- ``fp8`` (e4m3): worst-case half-ulp at 3 mantissa bits = 2^-4 of the
  row absmax for near-max elements; smaller elements keep ~relative
  precision down to the scaled denormal floor.
- ``int8``: uniform grid — half a step = 0.5/127 of the row absmax,
  everywhere.  Tighter than fp8 near the max, looser for tiny elements.

All-zero rows quantize to scale ``SCALE_EPS`` (0/0 -> 0, round-trip
exact); all-negative and denormal rows ride the same absmax math.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# e4m3 quantization recipe, shared by the XLA path, the fused Pallas
# pack kernel, and the KV-cache quantizer — wire producers must stay
# provably identical, so the constants live in exactly one place
E4M3_MAX = 448.0     # largest finite float8_e4m3fn value
INT8_MAX = 127.0     # symmetric int8 grid (|-128| excluded)
SCALE_EPS = 1e-12    # keeps all-zero rows at a finite scale (0/0 -> 0)

# u8 lanes appended per row: the first 4 carry the f32 scale.  128 keeps
# the message lane-aligned (the TPU wire moves 128-lane vectors).
SIDECAR = 128

WIRE_DTYPES = ("bf16", "int8", "fp8")
QUANTIZED_WIRE_DTYPES = ("int8", "fp8")

_PACK_BM = 128       # fused pack-kernel row block (see layers/moe.py note)


def is_quantized(wire_dtype: str) -> bool:
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype {wire_dtype!r} not in {WIRE_DTYPES}")
    return wire_dtype != "bf16"


def rel_error_bound(wire_dtype: str) -> float:
    """Worst-case |dequant - x| / row_absmax of one codec round-trip
    (the envelope the property tests pin; parity gates scale their
    ``assert_allclose`` tolerance from this — the ``verify_reduce``
    discipline of dtype-scaled bounds)."""
    return {"fp8": 2.0 ** -4, "int8": 0.5 / INT8_MAX, "bf16": 2.0 ** -8}[
        wire_dtype]


def abs_error_bound(absmax, wire_dtype: str):
    """The full ABSOLUTE per-element error envelope of one round-trip:
    ``rel_error_bound * row_absmax`` plus the ``SCALE_EPS`` additive
    floor — rows whose absmax sinks toward the epsilon (denormal-range
    or all-zero rows) have an eps-dominated scale, so their elements
    flush to zero with |err| = |x| <= SCALE_EPS-order, which the
    relative term alone does not cover.  The single source the property
    tests, the lint selftest, and the parity gates share."""
    return rel_error_bound(wire_dtype) * absmax + SCALE_EPS


def wire_itemsize(wire_dtype: str) -> int:
    """Bytes per element on the wire (payload only, sidecar excluded)."""
    return 1 if is_quantized(wire_dtype) else 2


def packed_width(h: int, wire_dtype: str) -> int:
    """Wire-message feature width in BYTES for an H-wide row."""
    if not is_quantized(wire_dtype):
        return 2 * h
    return h + SIDECAR


def wire_ratio(h: int, wire_dtype: str) -> float:
    """Quantized wire bytes / bf16 wire bytes for an H-wide row — the
    byte accounting ``bench.py wire`` gates (<= 0.55x at serving
    widths)."""
    return packed_width(h, wire_dtype) / (2.0 * h)


def _scale_for(absmax: jax.Array, wire_dtype: str) -> jax.Array:
    qmax = E4M3_MAX if wire_dtype == "fp8" else INT8_MAX
    return absmax / qmax + SCALE_EPS


def quantize_rows(x: jax.Array, wire_dtype: str = "fp8", *,
                  axis: int = -1):
    """Per-row quantization: returns ``(q, scale)`` with ``scale`` f32
    keeping the reduced ``axis`` at size 1, chosen so the row absmax
    maps to the dtype's max (448 for e4m3, 127 for int8).  ``q`` is
    ``float8_e4m3fn`` or ``int8``."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = _scale_for(absmax, wire_dtype)
    y = xf / scale
    if wire_dtype == "fp8":
        return y.astype(jnp.float8_e4m3fn), scale
    q = jnp.clip(jnp.round(y), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scale


def dequantize_rows(q: jax.Array, scale: jax.Array, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_rows` (both payload dtypes)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# one-message wire pack: payload bytes + f32 scale sidecar


def _payload_dtype(wire_dtype: str):
    return jnp.float8_e4m3fn if wire_dtype == "fp8" else jnp.int8


def _pack_kernel(wire_dtype, x_ref, o_ref):
    """One-pass quantize + wire pack: absmax -> scale -> payload bitcast
    to u8, with the f32 scale's 4 bytes spread onto the sidecar lanes by
    iota-select — one HBM read of the bf16 rows and one write of the u8
    message, vs the XLA path's materialized quantize + concat (measured
    100-166 GB/s XLA vs ~255 GB/s for this kernel at the bench shape;
    the number that pins the codec's wire economics in BENCH r04)."""
    xf = x_ref[...].astype(jnp.float32)                    # (bm, h)
    absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
    scale = _scale_for(absmax, wire_dtype)                 # (bm, 1)
    y = xf / scale
    if wire_dtype == "fp8":
        q = y.astype(jnp.float8_e4m3fn)
    else:
        q = jnp.clip(jnp.round(y), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    payload = jax.lax.bitcast_convert_type(q, jnp.uint8)   # (bm, h)
    si = jax.lax.bitcast_convert_type(scale, jnp.uint32)   # (bm, 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (q.shape[0], SIDECAR), 1)
    byte = jnp.right_shift(si, (jnp.minimum(lane, 3) * 8).astype(jnp.uint32))
    sidecar = jnp.where(lane < 4, byte & 0xFF, 0).astype(jnp.uint8)
    o_ref[...] = jnp.concatenate([payload, sidecar], axis=1)


@functools.lru_cache(maxsize=None)
def _build_pack(t: int, h: int, wire_dtype: str):
    from jax.experimental import pallas as pl

    from ..core import compilation

    return pl.pallas_call(
        functools.partial(_pack_kernel, wire_dtype),
        grid=(t // _PACK_BM,),
        in_specs=[pl.BlockSpec((_PACK_BM, h), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_PACK_BM, h + SIDECAR), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, h + SIDECAR), jnp.uint8),
        compiler_params=compilation.compiler_params(
            collective=False, dimension_semantics=("parallel",),
            # the f32 working tile exceeds the 16 MiB scoped default
            vmem_limit_bytes=64 * 2**20,
        ),
        interpret=compilation.interpret_mode(),
    )


def pack_quantized(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Assemble the wire message from ALREADY-quantized rows: payload
    bytes + the f32 scale's 4 bytes + zero padding to the ``SIDECAR``
    lanes.  The one home of the sidecar byte layout — shared by
    :func:`pack_rows`'s XLA path and callers that must ship exactly the
    ``(q, scale)`` a residual was accounted against (the AR error-
    feedback wire, ``comm.quantized._build_q_ar``)."""
    payload = jax.lax.bitcast_convert_type(q, jnp.uint8)
    sc = jax.lax.bitcast_convert_type(
        scale.astype(jnp.float32), jnp.uint8
    ).reshape(*q.shape[:-1], 4)
    pad = jnp.zeros((*q.shape[:-1], SIDECAR - 4), jnp.uint8)
    return jnp.concatenate([payload, sc, pad], axis=-1)


def _pack_rows_xla(x: jax.Array, wire_dtype: str) -> jax.Array:
    q, scale = quantize_rows(x, wire_dtype)            # (..., H), (..., 1)
    return pack_quantized(q, scale)


def pack_rows(x: jax.Array, wire_dtype: str = "fp8") -> jax.Array:
    """Quantize rows and pack payload + f32 scale sidecar into ONE uint8
    wire message ``(..., H + SIDECAR)``.  Runs the fused one-pass Pallas
    kernel when the shape tiles cleanly; odd shapes and the CPU backend
    take the XLA path (decoded-value equivalent; the fusion can shift
    the last payload/scale ulp under interpret mode — the CI tests
    assert decoded equivalence, not byte equality)."""
    if not is_quantized(wire_dtype):
        raise ValueError("pack_rows packs quantized wire dtypes only; "
                         "bf16 payloads ship unpacked")
    from ..core import platform

    if (x.ndim == 2 and x.shape[0] % _PACK_BM == 0
            and x.shape[1] % 128 == 0 and not platform.on_cpu()):
        return _build_pack(*x.shape, wire_dtype)(x)
    return _pack_rows_xla(x, wire_dtype)


def unpack_rows(u8: jax.Array, h: int, wire_dtype: str = "fp8",
                out_dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of :func:`pack_rows`: split payload/scale, dequantize."""
    q = jax.lax.bitcast_convert_type(u8[..., :h],
                                     _payload_dtype(wire_dtype))
    scale = jax.lax.bitcast_convert_type(
        u8[..., h:h + 4], jnp.float32
    )[..., None]
    return dequantize_rows(q, scale, out_dtype)


# ---------------------------------------------------------------------------
# error feedback (the AR option) and the reduction golden


def ef_quantize_rows(x: jax.Array, wire_dtype: str,
                     residual: jax.Array | None = None):
    """Error-feedback quantization step: fold the carried residual into
    the input BEFORE quantizing, return ``(q, scale, new_residual)``
    with ``new_residual = (x + residual) - dequant(q)`` in f32.  Carried
    across repeated quantized reductions, the residual cancels the
    codec's bias so the time-average converges to the exact sum instead
    of drifting (the standard EF-SGD treatment of compressed
    gradients)."""
    xc = x.astype(jnp.float32)
    if residual is not None:
        xc = xc + residual.astype(jnp.float32)
    q, scale = quantize_rows(xc, wire_dtype)
    new_res = xc - dequantize_rows(q, scale, jnp.float32)
    return q, scale, new_res


def roundtrip_rows(x: jax.Array, wire_dtype: str, *,
                   out_dtype=None) -> jax.Array:
    """One codec round-trip (quantize -> dequantize) — the value the
    consumer of a quantized wire actually sees.  The golden for parity
    gates and the integrity plane's quantized verifiers."""
    if not is_quantized(wire_dtype):
        return x if out_dtype is None else x.astype(out_dtype)
    q, scale = quantize_rows(x, wire_dtype)
    return dequantize_rows(q, scale,
                           out_dtype if out_dtype is not None else x.dtype)


def reduce_roundtrip(parts: jax.Array, wire_dtype: str,
                     out_dtype=None) -> jax.Array:
    """The exact value a quantized reduction delivers: per-partial codec
    round-trip, then an f32 sum.  ``parts``: (n, M, R) stacked partial
    addends.  This is the golden ``integrity.verify_reduce_q`` re-runs
    on the host (the quantized analogue of ``verify_reduce``'s f32
    re-reduction) and the local simulator the error-feedback
    convergence test drives."""
    deq = roundtrip_rows(parts, wire_dtype, out_dtype=jnp.float32)
    out = deq.sum(axis=0)
    return out if out_dtype is None else out.astype(out_dtype)
