"""Distributed device-language primitives (see primitives.py for the full
contract vs the reference's dl.* / libshmem_device)."""
from . import quant  # noqa: F401  (shared low-precision wire codecs)
from .primitives import (
    Team,
    rank, num_ranks, symm_at, notify, wait, peek, consume_token,
    remote_copy, local_copy, wait_recv, wait_send,
    barrier_all, barrier_neighbors,
    ring_neighbors, ring_src_rank, collective_prologue,
)
