"""The distributed primitive vocabulary, usable *inside* Pallas TPU kernels.

This module is the API contract of the framework, standing in for the whole
device-language stack of the reference:

- ``dl.rank/num_ranks/symm_at/notify/wait/consume_token``
  (``python/triton_dist/language/distributed_ops.py:56-111``)
- the ``libshmem_device`` facade's put/get/signal/barrier families
  (``python/triton_dist/language/extra/libshmem_device.py``,
  ``backends/nvidia/language/cuda/libnvshmem_device.py:101-965``)
- the PTX intrinsics layer (``language_extra.py``) — not needed on TPU:
  Mosaic provides fences/atomics semantics via semaphores and DMA ordering.

Semantics mapping (see also docs/primitives.md):

==================  =====================================================
reference           TPU-native (this module)
==================  =====================================================
rank()              ``rank(axis)`` -> `jax.lax.axis_index`
num_ranks()         ``num_ranks(axis)`` -> `jax.lax.axis_size`
symm_at(ptr, r)     remote refs are addressed by logical device id in
                    ``remote_copy``/``notify``; ``symm_at`` returns the id
notify(ptr, r, op)  ``notify(sem, device_id, inc)`` — semaphore signal at a
                    peer; counting (ADD) semantics.  SET-to-value protocols
                    are re-expressed as counts (SURVEY.md section 7).
wait(ptr, n, val)   ``wait(sem, value)`` — blocking semaphore wait
consume_token(t)    ``consume_token(x, token)`` — ordering no-op; Pallas
                    ref/DMA dataflow already orders compute after waits
putmem_signal       ``remote_copy(src, dst, send_sem, recv_sem, dst_rank)``
                    — RDMA with completion semaphores on both sides
getmem              TPU RDMA is push-only; pull = peer pushes (use
                    ``remote_copy`` from the owner) or XLA collectives
barrier_all         ``barrier_all(axis)`` — all-to-all semaphore barrier
fence/quiet         DMA completion semaphores subsume memory fencing
==================  =====================================================
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# ---------------------------------------------------------------------------
# protocol record mode (tdt.analysis)
#
# The static protocol verifier (``triton_distributed_tpu.analysis``) executes
# kernel BODIES as plain Python with symbolic refs/semaphores, one concrete
# rank at a time, and needs every primitive below to *describe* its event
# (semaphore identity, peer id, destination chunk) instead of lowering to
# Mosaic/interpret machinery.  When a recorder is installed on the current
# thread, each primitive forwards to it and returns; nothing in jax.pallas
# is touched (this is what makes the verifier run on a CPU-only container
# whose jax cannot even build a pallas_call).  See docs/static_analysis.md.

_RECORD_STATE = threading.local()


def active_recorder():
    """The protocol recorder capturing primitive events on this thread, or
    None (normal operation).  Installed by ``analysis.record.record_kernel``."""
    return getattr(_RECORD_STATE, "recorder", None)


def _set_recorder(rec) -> None:
    _RECORD_STATE.recorder = rec


# ---------------------------------------------------------------------------
# fault-injection scope (tdt.resilience)
#
# The fault harness (``triton_distributed_tpu.resilience.faults``) hooks the
# SAME interception points the recorder uses: when a scope is installed on
# the current thread, each primitive below consults it BEFORE dispatching —
# so a dropped signal never reaches the recorder (or the device), exactly as
# it would never reach the wire.  The scope may also raise ``RankAborted``
# to model a rank dying mid-kernel.  See docs/robustness.md.

_FAULT_STATE = threading.local()


def active_fault_scope():
    """The fault-injection scope intercepting primitives on this thread,
    or None (normal operation).  Installed by ``resilience.faults.scoped``."""
    return getattr(_FAULT_STATE, "scope", None)


def _set_fault_scope(scope) -> None:
    _FAULT_STATE.scope = scope


# ---------------------------------------------------------------------------
# flight recorder (tdt.obs)
#
# The flight recorder (``triton_distributed_tpu.obs.flight``) rides the
# SAME interception points: when its thread capture is installed (the
# record-mode harness) or the TDT_FLIGHT global ring is on, every primitive
# below reports its event — semaphore identity, destination chunk, peer,
# credit size, monotonic timestamp — BEFORE dispatching.  The hook sits
# after the fault scope's verdict (a dropped signal never reaches the
# flight stream, exactly as it never reaches the wire) and before the
# analysis recorder (both modes are captured).  See docs/observability.md.

_FLIGHT_MOD: list = []


def _flight():
    """The flight-recorder sink for this thread, or None (≈0 cost when
    the ring is off and no capture is installed)."""
    if not _FLIGHT_MOD:
        from ..obs import flight as fm

        _FLIGHT_MOD.append(fm)
    return _FLIGHT_MOD[0].active()


class _FlightLocalDesc:
    """Record-mode local-copy descriptor that reports its ``wait`` to the
    flight stream (the recorder's descriptor bypasses the primitives
    layer on ``.wait()``)."""

    def __init__(self, inner, fl, dst, sem):
        self._inner, self._fl, self._dst, self._sem = inner, fl, dst, sem

    def start(self) -> None:
        self._inner.start()

    def wait(self) -> None:
        self._fl.on_wait_recv(self._dst, self._sem)
        self._inner.wait()


# ---------------------------------------------------------------------------
# teams: axis-rank -> logical device id translation


@dataclasses.dataclass(frozen=True)
class Team:
    """A communicator over one mesh axis (reference: NVSHMEM teams / the
    torch TP process group, ``utils.py:190``).

    Pallas remote DMA and semaphore ops address peers by *linearized logical
    device id* over the whole mesh, while collective algorithms think in
    *ranks along one axis*.  On a multi-axis mesh (e.g. ``{"dp":2,"tp":4}``)
    those differ: tp-rank 1 seen from device (dp=1, tp=0) is logical id 5,
    not 1.  ``Team.device_id`` performs that translation by holding every
    mesh axis's (name, size) and substituting the peer's rank only on the
    team axis; all other coordinates are this device's own.
    """

    axes: tuple[tuple[str, int], ...]  # full mesh (name, size), outermost first
    axis: str                          # the team (collective) axis

    @classmethod
    def of(cls, mesh, axis: str) -> "Team":
        return cls(
            tuple((str(n), int(mesh.shape[n])) for n in mesh.axis_names),
            axis,
        )

    @property
    def size(self) -> int:
        return dict(self.axes)[self.axis]

    def rank(self) -> jax.Array:
        rec = active_recorder()
        if rec is not None:
            return rec.axis_rank(self.axis)
        return jax.lax.axis_index(self.axis)

    def device_id(self, peer_rank: jax.Array | int) -> jax.Array | int:
        """Logical device id of the team member with rank ``peer_rank``."""
        rec = active_recorder()
        if rec is not None:
            lid = 0
            for name, s in self.axes:
                idx = int(peer_rank) if name == self.axis \
                    else rec.axis_rank(name)
                lid = lid * s + idx
            return lid
        if len(self.axes) == 1:
            return peer_rank
        lid = None
        for name, s in self.axes:
            idx = peer_rank if name == self.axis else jax.lax.axis_index(name)
            lid = idx if lid is None else lid * s + idx
        return lid

    def neighbor_ranks(self) -> tuple[jax.Array, jax.Array]:
        """(left, right) team ranks on the ring."""
        me, n = self.rank(), self.size
        return jax.lax.rem(me + n - 1, n), jax.lax.rem(me + 1, n)


def _as_team(axis: "str | Team") -> Team:
    if isinstance(axis, Team):
        return axis
    rec = active_recorder()
    if rec is not None:
        return Team(((axis, rec.axis_size(axis)),), axis)
    # Single-axis view: correct when the mesh has only this axis; callers on
    # multi-axis meshes must pass a Team built with Team.of(mesh, axis).
    return Team(((axis, jax.lax.axis_size(axis)),), axis)


# ---------------------------------------------------------------------------
# identity


def rank(axis: str) -> jax.Array:
    """This device's index along a mesh axis (reference ``dl.rank``)."""
    rec = active_recorder()
    if rec is not None:
        return rec.axis_rank(axis)
    return jax.lax.axis_index(axis)


def num_ranks(axis: str) -> int:
    """Number of devices along a mesh axis (reference ``dl.num_ranks``)."""
    rec = active_recorder()
    if rec is not None:
        return rec.axis_size(axis)
    return jax.lax.axis_size(axis)


def symm_at(peer: jax.Array | int) -> jax.Array | int:
    """Resolve a peer's symmetric address: on TPU, remote memory is addressed
    by logical device id in the RDMA/semaphore ops, so the "remote pointer"
    IS the id (reference ``dl.symm_at`` -> ``nvshmem_ptr``)."""
    return peer


# ---------------------------------------------------------------------------
# signal / wait


def notify(
    sem,
    device_id: jax.Array | int | None = None,
    *,
    inc: int | jax.Array = 1,
) -> None:
    """Signal a (possibly remote) semaphore (reference ``dl.notify``;
    ``NotifyOp`` lowering ``DistributedOpToLLVM.cpp:233-430``).

    ``device_id=None`` signals the local semaphore.  Only ADD (counting)
    semantics exist on TPU; protocols written against SET re-encode the
    expected value as an arrival count.
    """
    scope = active_fault_scope()
    action = None
    if scope is not None:
        action = scope.on_notify(sem, device_id, inc)
        if action == "drop":
            # the signal is lost in flight: neither the recorder nor the
            # device semaphore ever sees it
            return
    fl = _flight()
    if fl is not None:
        fl.on_notify(sem, device_id, inc)
    rec = active_recorder()
    if rec is not None:
        rec.on_notify(sem, device_id, inc)
        if isinstance(action, tuple) and action[0] == "delay":
            scope.mark_delayed(len(rec.events) - 1, action[1])
        return
    if isinstance(action, tuple) and action[0] == "delay":
        # live mode has no host-side lever over in-flight signal latency
        scope.mark_live_unsupported("delay_notify")
    if device_id is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        pltpu.semaphore_signal(
            sem,
            inc=inc,
            device_id=device_id,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )


def wait(sem, value: int | jax.Array = 1) -> None:
    """Block until ``sem >= value``, consuming ``value`` (reference
    ``dl.wait``; spin-wait lowering ``DistributedOpToLLVM.cpp:146-219``).

    No device-side timeout exists: boundedness is the HOST's job (the
    ``resilience`` watchdog wraps the collective entry points with a
    perf-model-derived deadline and raises ``CollectiveTimeoutError``
    naming the pending semaphore instead of hanging — see
    docs/robustness.md)."""
    scope = active_fault_scope()
    action = scope.on_wait(sem, value) if scope is not None else None
    fl = _flight()
    if fl is not None:
        fl.on_wait(sem, value)
    rec = active_recorder()
    if rec is not None:
        rec.on_wait(sem, value)
        return
    if isinstance(action, tuple) and action[0] == "stale":
        # a leftover credit from a previous invocation: pre-credit the
        # local semaphore so this wait passes early (live injection)
        pltpu.semaphore_signal(sem, inc=action[1])
    pltpu.semaphore_wait(sem, value)


def peek(sem) -> jax.Array:
    """Non-blocking semaphore read (no reference analogue — the LL protocols
    poll flags in data; on TPU you can poll the count directly).

    Mosaic (real hardware) reads the live count.  The interpret backend
    has no ``semaphore_read`` rule (its big-if dispatch covers
    signal/wait/DMA), so under simulation ``peek`` returns the
    NON-BLOCKING LOWER BOUND 0: "the signal has not arrived yet".  That
    is the one approximation that preserves a polling protocol's
    correctness — a poller must already handle 0 (nothing arrived) by
    falling through to its blocking ``wait`` path, so under interpret
    mode it simply always takes that path; it can never be tricked into
    consuming data whose signal hasn't fired.  Count-reading ASSERTIONS
    still need hardware (``scripts/run_hw_markers.py``); count semantics
    under simulation are proven through exact-valued ``wait`` round
    trips (``tests/test_lang_primitives.py``)."""
    if active_recorder() is not None:
        raise NotImplementedError(
            "tdt.analysis record mode cannot model non-blocking peek: a "
            "polling protocol has no static wait-for structure to verify"
        )
    from ..core import platform

    if platform.on_cpu():
        # interpret-mode rule: the pessimistic non-blocking approximation
        # (platform.on_cpu, not compilation.interpret_mode, so the rule
        # resolves even on jax builds whose pltpu lacks InterpretParams)
        return jnp.zeros((), jnp.int32)
    return pltpu.semaphore_read(sem)


def consume_token(x: Any, token: Any = None) -> Any:
    """Ordering fence between a wait and a use (reference
    ``dl.consume_token``, lowered to an artificial data dependency).

    Pallas orders a ``wait`` before subsequent reads of the refs it guards,
    so this is an identity kept for API parity and readability.
    """
    del token
    return x


# ---------------------------------------------------------------------------
# data movement


def remote_copy(
    src,
    dst,
    send_sem,
    recv_sem,
    device_id: jax.Array | int,
    *,
    start: bool = True,
):
    """Push ``src`` (local ref/slice) into ``dst`` (peer's symmetric ref) —
    the reference's ``putmem_signal`` family (``nvshmem_wrapper.cu``,
    ``libnvshmem_device.py``): bulk RDMA plus a completion signal visible to
    the receiver (``recv_sem``) and to the sender (``send_sem``).

    Returns the descriptor; call ``.wait()`` (or ``wait_send``/``wait_recv``)
    to block.  ``start=False`` returns an unstarted descriptor.
    """
    scope = active_fault_scope()
    action = None
    if scope is not None:
        action = scope.on_remote_copy(src, dst, send_sem, recv_sem,
                                      device_id)
    fl = _flight()
    if fl is not None:
        fl.on_remote_copy(src, dst, send_sem, recv_sem, device_id)
    rec = active_recorder()
    if rec is not None:
        desc = rec.on_remote_copy(src, dst, send_sem, recv_sem, device_id,
                                  start=start)
        if action == "drop_recv":
            scope.mark_dropped_recv(len(rec.events) - 1)
        elif action == "corrupt":
            # the copy executes and credits normally; the PAYLOAD is
            # marked flipped in flight — only the checksum protocol
            # (resilience.integrity) can see it
            scope.mark_corrupt(len(rec.events) - 1)
        return desc
    if action == "drop_recv":
        # losing only the DMA completion signal (data landed, signal
        # didn't) is not expressible through the Pallas DMA API
        scope.mark_live_unsupported("drop_recv")
    elif action == "corrupt":
        # in-kernel payload bytes are not host-reachable at trace time;
        # live corruption injects through the consumer-side verification
        # layer instead (FaultScope.corrupt_result via integrity.checked)
        scope.mark_live_unsupported("corrupt_payload")
    copy = pltpu.make_async_remote_copy(
        src_ref=src,
        dst_ref=dst,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=device_id,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    if start:
        copy.start()
    return copy


def local_copy(src, dst, sem, *, start: bool = True):
    """Async local DMA (HBM<->VMEM) — the reference's cp.async / copy-engine
    path collapses to this on TPU."""
    scope = active_fault_scope()
    if scope is not None:
        scope.on_local_copy(src, dst, sem)
    fl = _flight()
    if fl is not None:
        fl.on_local_copy(src, dst, sem)
    rec = active_recorder()
    if rec is not None:
        desc = rec.on_local_copy(src, dst, sem, start=start)
        # the recorder's descriptor reports its .wait() directly to the
        # recorder; wrap it so the flight stream sees the wait too
        return desc if fl is None else _FlightLocalDesc(desc, fl, dst, sem)
    copy = pltpu.make_async_copy(src, dst, sem)
    if start:
        copy.start()
    return copy


def wait_recv(dst_ref, sem) -> None:
    """Block until a remote write into ``dst_ref`` has fully landed.

    A DMA semaphore counts bytes; constructing a same-shaped local descriptor
    and waiting it consumes exactly the incoming transfer's count.  This is
    the consumer side of ``remote_copy`` when producer and consumer are
    different points in the program (the reference's ``dl.wait`` on ready
    flags / ``signal_wait_until``).
    """
    scope = active_fault_scope()
    action = scope.on_wait_recv(dst_ref, sem) if scope is not None else None
    fl = _flight()
    if fl is not None:
        fl.on_wait_recv(dst_ref, sem)
    rec = active_recorder()
    if rec is not None:
        rec.on_wait_recv(dst_ref, sem)
        if action == "poison":
            # the guarded landing region is marked poisoned at rest
            # (settled DMA, bytes flipped before consumption)
            scope.mark_poisoned(len(rec.events) - 1)
        return
    if action == "poison":
        # at-rest flips of device memory are not host-reachable from a
        # traced kernel; live injection rides the entry-level hook
        # (FaultScope.corrupt_result) and the serve KV-audit cells
        scope.mark_live_unsupported("corrupt_kv_page")
    pltpu.make_async_copy(dst_ref, dst_ref, sem).wait()


def wait_send(src_ref, sem) -> None:
    """Drain one outgoing ``remote_copy`` of ``src_ref``'s shape/size (the
    reference's ``nvshmem_quiet`` per-transfer analogue).  Counting
    semantics: call once per outstanding send of this size."""
    scope = active_fault_scope()
    if scope is not None:
        scope.on_wait_send(src_ref, sem)
    fl = _flight()
    if fl is not None:
        fl.on_wait_send(src_ref, sem)
    rec = active_recorder()
    if rec is not None:
        rec.on_wait_send(src_ref, sem)
        return
    pltpu.make_async_copy(src_ref, src_ref, sem).wait()


# ---------------------------------------------------------------------------
# barriers


def barrier_all(axis: "str | Team", sem=None) -> None:
    """Full barrier over a mesh axis (reference ``barrier_all`` /
    ``barrier_all_intra_node_atomic_cas_block``, ``common_ops.py:135-205``).

    Hub (arrive/release) design rather than all-to-all: every rank signals
    rank 0; rank 0 waits for n-1 arrivals, then releases every other rank
    with one signal each.  With counting semaphores this is safe under
    REPEATED use of the same semaphore (and across kernel invocations
    sharing the global barrier semaphore): arrivals only ever target rank
    0's semaphore and releases only non-zero ranks', so a fast rank's
    round-k+1 signals can never satisfy a slow rank's round-k wait — the
    flaw of the naive all-to-all counting barrier.  O(n) messages, 2 hops.

    Uses the global barrier semaphore unless an explicit REGULAR semaphore
    is passed.  Kernels using the implicit barrier semaphore must set a
    ``collective_id`` in their CompilerParams.
    """
    team = _as_team(axis)
    fl = _flight()
    if fl is not None:
        fl.on_barrier("barrier_all", team, sem)
    rec = active_recorder()
    if rec is not None:
        rec.on_barrier_all(team, sem)
        return
    if sem is None:
        sem = pltpu.get_barrier_semaphore()
    me = team.rank()
    n = team.size
    if n == 1:
        return

    @pl.when(me != 0)
    def _():
        # arrive at the hub, then wait for the release
        pltpu.semaphore_signal(
            sem, inc=1, device_id=team.device_id(0),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        pltpu.semaphore_wait(sem, 1)

    @pl.when(me == 0)
    def _():
        pltpu.semaphore_wait(sem, n - 1)

        def release(i, _):
            pltpu.semaphore_signal(
                sem, inc=1, device_id=team.device_id(i + 1),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            return 0

        jax.lax.fori_loop(0, n - 1, release, 0)


def barrier_neighbors(axis: "str | Team", sem=None) -> None:
    """Barrier with ring neighbors only — cheaper than ``barrier_all`` when a
    kernel only exchanges with adjacent ranks (the common ring case).

    CAVEAT — no round separation: a fast neighbor's next-round signals can
    satisfy this round's wait, so the only guarantee under repeated use is
    that neighbors are within one round of each other.  That is sufficient
    for ring kernels whose per-chunk writes are individually gated by DMA
    semaphores (the normal pattern), but NOT a true barrier.  Use
    ``barrier_all`` (round-safe hub design) when in doubt;
    ``collective_prologue`` defaults to it.
    """
    team = _as_team(axis)
    fl = _flight()
    if fl is not None:
        fl.on_barrier("barrier_neighbors", team, sem)
    rec = active_recorder()
    if rec is not None:
        rec.on_barrier_neighbors(team, sem)
        return
    if sem is None:
        sem = pltpu.get_barrier_semaphore()
    if team.size == 1:
        return
    left, right = team.neighbor_ranks()
    pltpu.semaphore_signal(sem, inc=1, device_id=team.device_id(left),
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(sem, inc=1, device_id=team.device_id(right),
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(sem, 2)


def collective_prologue(axis: "str | Team", *, neighbors_only: bool = False) -> None:
    """Entry barrier every collective kernel must run before its first remote
    write.

    Rationale (same on real TPU and in interpret mode): a remote DMA may land
    in a peer's buffer before that peer has entered the kernel — on hardware
    the buffer may still be read by the peer's *previous* computation (XLA
    reuses buffers), and in interpret mode the buffer may not exist yet.  The
    reference has the same invariant: every op starts with
    ``local_copy_and_barrier_all`` / ``barrier_all_on_stream``
    (``allgather_gemm.py:101-117``, ``common_ops.py``).

    ``neighbors_only=True`` is sufficient for ring kernels where only ring
    neighbors ever write to us.
    """
    if neighbors_only:
        barrier_neighbors(axis)
    else:
        barrier_all(axis)


# ---------------------------------------------------------------------------
# ring topology helpers


def ring_neighbors(axis: "str | Team") -> tuple[jax.Array, jax.Array]:
    """(left, right) logical device ids of ring neighbors along ``axis``."""
    team = _as_team(axis)
    left, right = team.neighbor_ranks()
    return team.device_id(left), team.device_id(right)


def ring_src_rank(axis: "str | Team", step: jax.Array | int) -> jax.Array:
    """Rank whose chunk arrives at this device after ``step`` forwarding hops
    in a +1 ring (chunk origin at ring distance step+1 to the left)."""
    team = _as_team(axis)
    me, n = team.rank(), team.size
    return jax.lax.rem(me + (2 * n) - step - 1, n)
