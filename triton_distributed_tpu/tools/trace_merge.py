"""Multi-rank trace merging: native fast path + Python fallback.

Reference: ``python/triton_dist/utils.py:414-584`` — per-rank chrome
traces are gathered, pid/tid-remapped (``process_trace_json:365``) and
merged (``_merge_json_v2:465``), with a multiprocessing JSON dumper
(``ParallelJsonDumper:414``) because CPython JSON IO is the bottleneck.
Here the merge itself is native C++ (``csrc/trace_merge.cc``: single pass
per file, no JSON DOM, zlib gzip), compiled on demand with the system
toolchain and loaded via ctypes; when no compiler is available the
pure-Python fallback produces identical output.
"""

from __future__ import annotations

import ctypes
import gzip
import json
from typing import Sequence

from .native import load_native

_PID_OFFSET = 1_000_000

_typed = {"done": False}


def _load_native():
    """Build/load the native merger via ``tools.native``; False if
    impossible."""
    lib = load_native("trace_merge.cc", ldflags=("-lz",))
    if lib and not _typed["done"]:
        lib.tdt_merge_traces.restype = ctypes.c_int
        lib.tdt_merge_traces.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        _typed["done"] = True
    return lib


def _merge_python(inputs: Sequence[str], ranks: Sequence[int],
                  out_path: str, gzip_out: bool,
                  ts_offsets: Sequence[float] | None = None) -> None:
    envelope = None
    events = []
    for i, (path, rank) in enumerate(zip(inputs, ranks)):
        with open(path) as f:
            trace = json.load(f)
        if envelope is None:
            # keep the first input's non-event keys (displayTimeUnit, ...)
            envelope = {k: v for k, v in trace.items() if k != "traceEvents"}
        off = ts_offsets[i] if ts_offsets is not None else 0
        for ev in trace.get("traceEvents", []):
            if isinstance(ev.get("pid"), int):
                ev["pid"] += rank * _PID_OFFSET
            if off and isinstance(ev.get("ts"), (int, float)):
                ev["ts"] += off
            events.append(ev)
    envelope = dict(envelope or {})
    envelope["traceEvents"] = events
    # compact separators: the native path splices the inputs' own JSON
    # text (joining files with a bare ','), so on compact inputs whose
    # envelope puts traceEvents last — the layout ``obs.tracing.export``
    # writes — the two paths produce byte-identical merged output
    # (tests/test_tools.py pins this)
    data = json.dumps(envelope, separators=(",", ":")).encode()
    opener = gzip.open if gzip_out else open
    with opener(out_path, "wb") as f:
        f.write(data)


def merge_traces(
    inputs: Sequence[str],
    ranks: Sequence[int] | None = None,
    out_path: str = "merged_trace.json.gz",
    *,
    gzip_out: bool | None = None,
    native: bool = True,
    ts_offsets: Sequence[float] | None = None,
) -> str:
    """Merge per-rank chrome traces into one file, offsetting each rank's
    pids by ``rank * 1e6`` so process lanes stay disjoint in the viewer.

    ``ts_offsets`` (us per input, e.g. from
    ``obs.timeline.align_clocks`` over flight barrier events) shifts each
    input's event timestamps before merging — cross-process clock
    alignment so one global timeline lines up at the barriers.  Offsets
    force the Python merge path: the native merger splices input text
    verbatim and cannot rewrite ``ts``.

    Returns ``out_path``.  ``gzip_out`` defaults to the ``.gz`` suffix.
    """
    if ranks is None:
        ranks = list(range(len(inputs)))
    if len(ranks) != len(inputs):
        raise ValueError(f"{len(inputs)} inputs but {len(ranks)} ranks")
    if ts_offsets is not None and len(ts_offsets) != len(inputs):
        raise ValueError(
            f"{len(inputs)} inputs but {len(ts_offsets)} ts_offsets")
    if gzip_out is None:
        gzip_out = out_path.endswith(".gz")

    if ts_offsets is not None and any(ts_offsets):
        native = False
    lib = _load_native() if native else False
    if lib:
        arr = (ctypes.c_char_p * len(inputs))(
            *[p.encode() for p in inputs]
        )
        rk = (ctypes.c_int * len(inputs))(*list(ranks))
        rc = lib.tdt_merge_traces(arr, rk, len(inputs),
                                  out_path.encode(), int(gzip_out))
        if rc == 0:
            return out_path
        # fall through to the Python path on any native error
    _merge_python(inputs, ranks, out_path, gzip_out, ts_offsets)
    return out_path
