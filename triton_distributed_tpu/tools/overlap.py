"""Measured DMA/MXU overlap of the matmul pipeline (VERDICT r4 next #6).

The reference proves its fused kernels hide communication with hardware
charts (``asset/ag-gemm-intra-node.png``); this repo's
``tests/test_overlap_structure.py`` proves the PROGRAM ORDER admits
overlap but never measures it.  The v5e's profiler trace exposes a
Pallas kernel as ONE opaque custom-call (no DMA-vs-MXU interval lines —
checked: ``TC Overlay`` is empty on this toolchain), so the measured
proof here is a three-kernel DECOMPOSITION of the same tile pipeline:

- **fused**: the real pipelined matmul — per grid step, fetch the
  (bm, bk)/(bk, bn) blocks and run the MXU dot.
- **dma-only**: identical grid and BlockSpecs (identical HBM traffic
  through the same pipeline), with the dot replaced by a touch of one
  element per block — the wall time of the memory stream alone.
- **mxu-only**: identical grid and dot sequence, but the A/B index maps
  pin to block (0, 0) — Mosaic's pipeline elides consecutive identical
  fetches (the grouped-matmul pad-elision mechanism), so after the first
  step the MXU runs from resident VMEM — the wall time of the compute
  alone.

If the pipeline overlaps perfectly, ``t_fused ~= max(t_dma, t_mxu)``;
if it serializes, ``t_fused ~= t_dma + t_mxu``.  The reported

    overlap_hidden_pct = (t_dma + t_mxu - t_fused) / min(t_dma, t_mxu)

is the fraction of the SMALLER phase hidden under the larger (1.0 =
fully hidden, 0.0 = fully serialized), clamped to [0, 1] against
measurement noise.  On a multi-chip slice the same decomposition applies
to the fused collective GEMMs' ring steps; the v5p >= 90%-hidden target
(BASELINE.json) inherits this metric.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import compilation
from ..core.utils import cdiv

_VL = 100 * 2**20  # the raised scoped-VMEM budget the matmul tiles use


def _mm_kernel(nk: int, mode: str, a_ref, b_ref, o_ref, acc_ref):
    kk = pl.program_id(2)
    if mode == "dma":
        # consume one (8, 128) corner of each fetched block so the
        # fetches are load-bearing (Mosaic rejects scalar VMEM reads),
        # then fill the output tile (VPU cost ~1 us per step, negligible
        # next to the block DMAs)
        touch = (jnp.sum(a_ref[0:8, 0:128].astype(jnp.float32))
                 + jnp.sum(b_ref[0:8, 0:128].astype(jnp.float32)))
        o_ref[...] = jnp.full(o_ref.shape, touch, o_ref.dtype)
        return

    @pl.when(kk == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot(
        a_ref[...], b_ref[...], preferred_element_type=jnp.float32
    )

    @pl.when(kk == nk - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _build(m, n, k, bm, bn, bk, mode, dtype):
    nk = cdiv(k, bk)
    if mode == "mxu":
        # pinned index maps: the pipeline elides the repeat fetches, so
        # the dots run from VMEM-resident blocks after step one
        a_map = lambda i, j, kk: (0, 0)      # noqa: E731
        b_map = lambda i, j, kk: (0, 0)      # noqa: E731
    else:
        a_map = lambda i, j, kk: (i, kk)     # noqa: E731
        b_map = lambda i, j, kk: (kk, j)     # noqa: E731
    call = pl.pallas_call(
        functools.partial(_mm_kernel, nk, mode),
        grid=(cdiv(m, bm), cdiv(n, bn), nk),
        in_specs=[
            pl.BlockSpec((bm, bk), a_map),
            pl.BlockSpec((bk, bn), b_map),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=_VL,
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def overlap_kernels(m: int, n: int, k: int, *, bm: int = 1024,
                    bn: int = 1024, bk: int = 512, dtype=jnp.bfloat16):
    """(fused, dma_only, mxu_only) jitted kernels of one tile pipeline —
    identical grids; see the module docstring for what each isolates."""
    return tuple(
        _build(m, n, k, bm, bn, bk, mode, jnp.dtype(dtype))
        for mode in ("fused", "dma", "mxu")
    )


def hidden_pct(t_fused: float, t_dma: float, t_mxu: float) -> float:
    """Fraction of the smaller phase hidden under the larger (pure math;
    clamped to [0, 1] against measurement noise)."""
    lo = min(t_dma, t_mxu)
    if lo <= 0:
        return 0.0
    return max(0.0, min(1.0, (t_dma + t_mxu - t_fused) / lo))
