"""Measured link calibration feeding collective method choice.

Reference: the NIC/NVLink probes that feed its perf models and method
selection — ``python/triton_dist/kernels/nvidia/comm_perf_model.py:92-129``
(per-link bandwidth by topology) and ``python/triton_dist/utils.py:587-862``
(NVLink fullmesh/speed, PCIe gen, NUMA probing).  VERDICT r4 next #5.

TPU translation: the quantities that decide between collective methods
are the per-hop LATENCY and per-chip BANDWIDTH of each wire class (ICI
within a slice, DCN across slices).  ``calibrate()`` measures both with
a size-swept ``ppermute`` (one neighbor hop per step): the wall time of
one hop is ``t(S) = L + S / bw``, so a linear fit over sizes gives
``L`` (intercept) and ``bw`` (1/slope).  Results persist beside the
autotune cache and every later process derives its crossovers from them:

- AllGather push-vs-ring (``comm.allgather.choose_method``): one-shot
  push wins while the payload is latency-dominated.  The crossover is
  the bandwidth-delay product ``L * bw`` — with the v5e's ~1.4 us hop
  and ~186 GB/s that is ~256 KiB, which is exactly the "MTU-ish"
  constant rounds 1-4 pinned by reasoning alone.
- AllReduce one-shot-vs-two-shot (``comm.allreduce.choose_method``):
  one-shot trades (n-1)x wire volume for a single hop of latency; the
  two-shot pays 2(n-1) latency-chained steps.  Crossover at ~2x the
  bandwidth-delay product (512 KiB cold default).

Cold-start (no calibration on disk) keeps those constants, so behavior
without a calibration run is exactly the documented round-4 behavior.

Run on a real slice:  python -m triton_distributed_tpu.tools.calibrate
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp

# Cold-start crossovers (docs/perf.md "Collective size crossovers"
# bullet — pinned by reasoning, superseded by a calibration run on a
# real slice).
DEFAULT_PUSH_BYTES = 256 * 1024
DEFAULT_ONE_SHOT_BYTES = 512 * 1024


def calibration_path() -> str:
    return os.environ.get(
        "TDT_LINKCAL_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "triton_distributed_tpu", "linkcal.json"),
    )


@dataclasses.dataclass(frozen=True)
class LinkCalibration:
    """Measured wire-class characteristics of the live topology.

    ``num_slices`` / ``chips_per_slice`` persist the SLICE TOPOLOGY the
    hierarchical collectives' chunk schedule consumes
    (``comm.hierarchical.chunk_schedule`` — the FAST-style emission
    order needs to know which peer groups ride which wire class without
    a live mesh in hand): one slice per process group on multislice
    TPU, measured at calibration time alongside the wire speeds."""

    ici_gbps: float | None = None      # per-chip neighbor-hop bandwidth
    ici_hop_us: float | None = None    # per-hop latency
    dcn_gbps: float | None = None      # cross-slice, per chip
    dcn_hop_us: float | None = None
    device_kind: str = ""
    n_devices: int = 0
    num_slices: int = 1                # DCN extent (process groups)
    chips_per_slice: int = 0           # ICI extent within one slice

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "LinkCalibration":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


_cached: LinkCalibration | None = None
_cached_path: str | None = None


def load_calibration() -> LinkCalibration | None:
    """The persisted calibration, or None (cold start).  Cached per path
    so hot method-choice paths pay a dict lookup, not file IO."""
    global _cached, _cached_path
    path = calibration_path()
    if _cached_path == path:
        return _cached
    try:
        with open(path) as f:
            _cached = LinkCalibration.from_json(json.load(f))
    except (OSError, ValueError, TypeError):
        _cached = None
    _cached_path = path
    return _cached


def save_calibration(cal: LinkCalibration) -> None:
    global _cached, _cached_path, _agreed
    _agreed = None   # derived thresholds must re-agree on new numbers
    path = calibration_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cal.to_json(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    _cached, _cached_path = cal, path


def invalidate_cache() -> None:
    """Drop the in-process calibration cache (tests; after re-calibration
    by another process)."""
    global _cached, _cached_path, _agreed
    _cached = _cached_path = None
    _agreed = None


# ---------------------------------------------------------------------------
# fitting


def fit_latency_bandwidth(sizes_bytes, times_s) -> tuple[float, float]:
    """Least-squares fit of ``t(S) = L + S / bw`` -> (hop_us, gbps).

    Pure math (unit-tested with synthetic points); negative intercepts
    (possible when noise exceeds the true latency at the smallest size)
    clamp to 0.
    """
    import numpy as np

    s = np.asarray(sizes_bytes, np.float64)
    t = np.asarray(times_s, np.float64)
    if len(s) < 2 or len(s) != len(t):
        raise ValueError("need >= 2 (size, time) points")
    slope, intercept = np.polyfit(s, t, 1)
    if slope <= 0:
        raise ValueError(
            f"non-physical fit (slope {slope:g} s/byte <= 0): timing noise "
            f"exceeded the size effect; re-run with larger sizes"
        )
    return max(intercept, 0.0) * 1e6, 1.0 / slope / 1e9


def _measure_hop(mesh, axis: str, sizes_bytes) -> tuple[float, float]:
    """Time one +1-neighbor ``ppermute`` hop at each size; fit L and bw."""
    from ..core.utils import perf_func

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]
    times = []
    for nbytes in sizes_bytes:
        rows = max(1, nbytes // (128 * 4))
        x = jnp.zeros((n * rows, 128), jnp.float32)

        def hop(x):
            return jax.lax.ppermute(x, axis, perm)

        from ..core import compilation

        fn = compilation.jit_shard_map(
            hop, mesh,
            in_specs=jax.sharding.PartitionSpec(axis),
            out_specs=jax.sharding.PartitionSpec(axis),
        )
        _, ms = perf_func(lambda: fn(x), iters=32, warmup_iters=3)
        times.append(ms / 1e3)
    sizes_actual = [max(1, b // (128 * 4)) * 128 * 4 for b in sizes_bytes]
    return fit_latency_bandwidth(sizes_actual, times)


def calibrate(mesh=None, *, save: bool | None = None,
              sizes_bytes=(64 * 1024, 512 * 1024, 2 * 2**20, 8 * 2**20),
              force: bool = False) -> LinkCalibration:
    """Measure the live topology's wire classes and persist the result.

    ICI needs a >= 2-device mesh; DCN needs >= 2 processes.  On a single
    chip there is nothing to measure and this raises — cold-start
    defaults remain in force.  ``force=True`` measures anyway (e.g.
    interpret-mode smoke tests); those numbers are simulation artifacts,
    so ``save=None`` (the default) resolves to "persist only on real
    hardware" — interpret-mode results are never written unless the
    caller passes an explicit ``save=True``.
    """
    from ..core import compilation, mesh as mesh_lib, platform

    if save is None:
        save = not compilation.interpret_mode()
    if compilation.interpret_mode() and not force:
        raise RuntimeError(
            "calibration on the interpret backend measures the simulator; "
            "run on real hardware (or pass force=True in tests)"
        )
    if mesh is None:
        mesh = mesh_lib.tp_mesh()
    axis = mesh.axis_names[-1]
    if mesh.shape[axis] < 2:
        raise RuntimeError(
            f"cannot measure {axis!r} links on a 1-device mesh; "
            f"cold-start defaults remain in force"
        )
    ici_us, ici_gbps = _measure_hop(mesh, axis, sizes_bytes)
    dcn_us = dcn_gbps = None
    if jax.process_count() > 1:
        # cross-process hops ride the DCN: a mesh whose outer "dcn" axis
        # spans processes (the hierarchical collectives' convention,
        # mesh.DCN_AXES) measures the slow wire class.  On the CPU
        # (interpret) platform the mesh leaves the spare devices OUT —
        # a full-occupancy collective mesh can park every XLA client
        # pool thread (core/platform.py force_cpu docstring)
        import numpy as np

        per = jax.device_count() // jax.process_count()
        if platform.on_cpu():
            per = max(1, per - platform.SPARE_VIRTUAL_DEVICES)
        devs = np.array(jax.devices()).reshape(
            jax.process_count(), -1
        )[:, :per]
        from jax.sharding import Mesh

        dcn_us, dcn_gbps = _measure_hop(
            Mesh(devs, ("dcn", "ici")), "dcn", sizes_bytes
        )
        # AGREEMENT across processes (core.utils.process_mean — the
        # same invariant the autotuner's rank-synced winner choice
        # upholds): thresholds derived from per-host calibrations feed
        # choose_method, and hosts disagreeing on push-vs-ring launch
        # MISMATCHED collective kernels — every host must persist the
        # identical (mean) numbers
        from ..core.utils import process_mean

        ici_us, ici_gbps, dcn_us, dcn_gbps = process_mean(
            [ici_us, ici_gbps, dcn_us, dcn_gbps]
        )
    cal = LinkCalibration(
        ici_gbps=round(ici_gbps, 2), ici_hop_us=round(ici_us, 3),
        dcn_gbps=None if dcn_gbps is None else round(dcn_gbps, 2),
        dcn_hop_us=None if dcn_us is None else round(dcn_us, 3),
        device_kind=platform.device_kind(),
        n_devices=jax.device_count(),
        # slice topology (ISSUE 10): one slice per process group — the
        # persisted shape the hierarchical chunk schedule keys on
        num_slices=jax.process_count(),
        chips_per_slice=jax.device_count() // max(jax.process_count(), 1),
    )
    if save:
        save_calibration(cal)
    return cal


# ---------------------------------------------------------------------------
# derived crossovers (the values comm.* method choice consumes)


def _bdp_bytes(cal: LinkCalibration | None) -> float | None:
    if cal is None or not cal.ici_gbps or cal.ici_hop_us is None:
        return None
    # a measured hop_us of exactly 0.0 (noise-clamped intercept) is a
    # REAL ultra-low-latency calibration, not a cold start: floor the
    # BDP at one wire MTU-ish chunk rather than discarding the
    # measurement through a falsy-zero check
    return max(cal.ici_gbps * 1e9 * cal.ici_hop_us * 1e-6, 8192.0)


# Cross-host agreement (ADVICE r5 low #5): the thresholds feed
# choose_method, and choose_method selects which collective KERNEL every
# host launches — hosts disagreeing on push-vs-ring launch MISMATCHED
# kernels and deadlock the mesh (exactly the divergence hazard
# analysis.checks flags statically).  A per-host ~/.cache linkcal.json
# gives no load-time guarantee: one host may lack the file or hold a
# stale one.  So in multi-process runs the DERIVED thresholds are
# agreed at first use: every process computes the cross-process mean
# and relative spread (via process_mean of values and squares — both
# identical on every host); agreement within tolerance adopts the mean,
# disagreement falls back to the cold defaults (also identical
# everywhere) and counts a ``resilience_degraded_calls`` event.

AGREE_REL_TOL = 0.05

_agreed: tuple[int, int] | None = None


def agree_thresholds(push_local: float, one_shot_local: float, *,
                     n_proc: int | None = None, mean_fn=None,
                     rel_tol: float = AGREE_REL_TOL) -> tuple[int, int]:
    """Resolve (push, one_shot) thresholds identically on every process.

    ``mean_fn``/``n_proc`` are injectable for tests; production uses
    ``core.utils.process_mean`` and ``jax.process_count``.

    CONTRACT (multi-process): ``process_mean`` is a COLLECTIVE — every
    process must reach it together.  First use is naturally aligned
    (the thresholds are consulted from ``choose_method`` at SPMD
    program points every host executes identically), and the result is
    memoized per process.  Consequently the memo must be invalidated on
    EVERY process or none: ``save_calibration``/``invalidate_cache``
    reset only the local memo, so re-calibrating one host of a live
    multi-host job without the others invalidating too would have that
    host issue a collective its peers never join.  Re-calibration is a
    whole-job (all-hosts) operation, same as the calibration run itself.
    """
    if n_proc is None:
        n_proc = jax.process_count()
    if n_proc == 1:
        return int(push_local), int(one_shot_local)
    if mean_fn is None:
        from ..core.utils import process_mean as mean_fn
    p, o = float(push_local), float(one_shot_local)
    mp, mo, mp2, mo2 = mean_fn([p, o, p * p, o * o])

    def rel_spread(m, m2) -> float:
        var = max(m2 - m * m, 0.0)
        return (var ** 0.5) / m if m else 0.0

    if rel_spread(mp, mp2) > rel_tol or rel_spread(mo, mo2) > rel_tol:
        from .. import obs

        if obs.enabled():
            obs.counter("resilience_degraded_calls", op="calibrate",
                        reason="threshold_disagreement").inc()
        return DEFAULT_PUSH_BYTES, DEFAULT_ONE_SHOT_BYTES
    return int(round(mp)), int(round(mo))


def _thresholds() -> tuple[int, int]:
    """Local derivation + (memoized) cross-process agreement."""
    global _agreed
    if _agreed is not None:
        return _agreed
    bdp = _bdp_bytes(load_calibration())
    push = int(bdp) if bdp is not None else DEFAULT_PUSH_BYTES
    one = int(2 * bdp) if bdp is not None else DEFAULT_ONE_SHOT_BYTES
    _agreed = agree_thresholds(push, one)
    return _agreed


# ---------------------------------------------------------------------------
# wire-codec economics (the fp8/int8 payload "auto" policy)

# The codec's measured pack+unpack throughput at the serving bench shape
# (BENCH r04 ``codec_gbps`` — input GB/s through the fused Pallas pack +
# XLA unpack).  Conservative: the one-pass pack alone measured ~255.
DEFAULT_CODEC_GBPS = 100.0


def wire_gbps(wire_class: str) -> float:
    """Per-chip bandwidth of a wire class: the MEASURED calibration when
    one exists, else the perf-model defaults (the documented v5e numbers
    — cold-start behavior identical to the pre-calibration policy)."""
    from . import perf_model

    cal = load_calibration()
    if wire_class == "dcn":
        if cal is not None and cal.dcn_gbps:
            return float(cal.dcn_gbps)
        return float(perf_model.DCN_GBPS_PER_CHIP)
    if cal is not None and cal.ici_gbps:
        return float(cal.ici_gbps)
    return float(perf_model.chip_spec().ici_gbps)


def codec_pays(wire_class: str, h: int = 7168, *,
               codec_gbps: float | None = None) -> bool:
    """Whether a quantized wire payload wins NET time on ``wire_class``
    at row width ``h``: the wire time the halved payload saves must
    exceed what the codec costs (pack send-side + unpack recv-side).
    This is the measured-threshold form of the old hard-coded
    "codec on DCN only" rule (``layers.moe.fp8_wire='auto'``): with the
    cold-start numbers it reproduces exactly that policy (BENCH r04
    ``net_us_per_token_hop_ici`` < 0 < ``_dcn``), and a calibration run
    on a live topology moves the crossover with the real link speeds."""
    from ..lang import quant

    saved_bytes = 2 * h - quant.packed_width(h, "fp8")
    if saved_bytes <= 0:
        return False
    codec = codec_gbps if codec_gbps is not None else DEFAULT_CODEC_GBPS
    codec_s = (2 * h) / (codec * 1e9)          # bf16 input through codec
    wire_s = saved_bytes / (wire_gbps(wire_class) * 1e9)
    return wire_s > codec_s


def push_bytes_threshold() -> int:
    """AllGather one-shot-push vs ring crossover (bytes per shard): the
    measured bandwidth-delay product, else the 256 KiB cold default;
    cross-process agreed (cold defaults on disagreement)."""
    return _thresholds()[0]


def one_shot_bytes_threshold() -> int:
    """AllReduce one-shot vs two-shot crossover (bytes per rank): ~2x
    the bandwidth-delay product (the two-shot pays 2(n-1) chained hops),
    else the 512 KiB cold default; cross-process agreed (cold defaults
    on disagreement)."""
    return _thresholds()[1]


def slice_topology() -> tuple[int, int]:
    """(num_slices, chips_per_slice) of the persisted calibration, else
    of the live process/device layout, else the single-slice default —
    the topology model the hierarchical chunk schedule consumes
    (``comm.hierarchical.chunk_schedule``)."""
    cal = load_calibration()
    if cal is not None and cal.num_slices >= 1 and cal.chips_per_slice >= 1:
        return int(cal.num_slices), int(cal.chips_per_slice)
    try:
        procs = jax.process_count()
        per = jax.device_count() // max(procs, 1)
        return max(procs, 1), max(per, 1)
    except Exception:
        return 1, 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="measure per-wire-class link characteristics and "
                    "persist them (plus the slice topology) beside the "
                    "autotune cache")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output: one JSON object "
                         "(calibration + derived thresholds), nothing else")
    args = ap.parse_args(argv)
    cal = calibrate()
    if args.json:
        print(json.dumps({
            **cal.to_json(),
            "push_bytes_threshold": push_bytes_threshold(),
            "one_shot_bytes_threshold": one_shot_bytes_threshold(),
            "path": calibration_path(),
        }, sort_keys=True))
        return 0
    print(json.dumps(cal.to_json()))
    print(f"-> push threshold {push_bytes_threshold()} B, "
          f"one-shot threshold {one_shot_bytes_threshold()} B "
          f"(persisted at {calibration_path()})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
