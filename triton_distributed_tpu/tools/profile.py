"""Profiling helpers: multi-host trace capture and scoped annotations.

Reference: ``python/triton_dist/utils.py:500-584`` — ``group_profile``
starts a torch profiler on every rank and merges the per-rank traces into
one artifact directory.

TPU translation: ``jax.profiler`` already writes per-host traces that
TensorBoard/XProf merges by design, so "merge" collapses into writing
every host's trace under ONE logdir; the context manager below adds the
reference's ergonomics (a name, rank-disambiguated subdirs, enable flag).
Device-side timeline detail comes for free from XLA's instrumentation —
including the Pallas kernels and the collectives this framework emits.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def group_profile(name: str = "trace", logdir: str = "/tmp/tdt_profile",
                  *, enabled: bool = True):
    """Capture a trace of the enclosed block on every process into a shared
    logdir (reference ``group_profile``).  View with TensorBoard/XProf.

    Multi-process runs write rank-disambiguated subdirs
    (``logdir/name/procN``) so per-host captures on a shared filesystem
    never clobber each other's artifacts; single-process runs keep the
    flat ``logdir/name`` path."""
    if not enabled:
        yield None
        return
    path = os.path.join(logdir, name)
    if jax.process_count() > 1:
        path = os.path.join(path, f"proc{jax.process_index()}")
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield path


def annotate(name: str):
    """Scoped trace annotation visible in the profile timeline (reference:
    torch.profiler.record_function)."""
    return jax.profiler.TraceAnnotation(name)


def memory_stats() -> dict:
    """Per-device live-memory snapshot (reference: the CUDA memory probes
    in ``utils.py``); empty on backends without memory_stats support."""
    out = {}
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            out[str(d)] = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
    return out
