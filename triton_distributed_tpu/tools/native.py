"""On-demand build + load of the framework's native C++ components.

Reference: the reference ships its host-side native code prebuilt through
``python/setup.py``'s cmake superbuild (``csrc/``, ``shmem/`` runtimes).
Here the native pieces are small single-file C++ libraries (``csrc/``)
compiled lazily with the system toolchain and loaded via ctypes — no
build step, no bindings dependency — and every consumer keeps a
pure-Python fallback for toolchain-less hosts.

Shared by ``tools.trace_merge`` (chrome-trace merger) and
``models.safetensors_io`` (weight-file reader).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_CSRC_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
)

_loaded: dict[str, "ctypes.CDLL | bool"] = {}


def cache_dir() -> str:
    return os.environ.get(
        "TDT_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "triton_distributed_tpu"),
    )


def load_native(src_name: str, *, ldflags: tuple[str, ...] = ()) -> (
        "ctypes.CDLL | bool"):
    """Compile ``csrc/<src_name>`` (once, rebuilt when the source is newer
    than the cached .so) and dlopen it.  Returns False when the toolchain
    or source is unavailable — callers fall back to their Python paths.
    """
    key = src_name + ":" + " ".join(ldflags)
    if key in _loaded:
        return _loaded[key]
    src = os.path.join(_CSRC_DIR, src_name)
    # flags participate in the artifact name: a flags change must rebuild,
    # not silently reuse a stale .so whose mtime looks current
    stem = os.path.splitext(src_name)[0]
    if ldflags:
        import hashlib

        stem += "-" + hashlib.sha1(
            " ".join(ldflags).encode()
        ).hexdigest()[:8]
    so = os.path.join(cache_dir(), stem + ".so")
    try:
        if not os.path.exists(so) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(so)
        ):
            os.makedirs(os.path.dirname(so), exist_ok=True)
            tmp = so + f".tmp.{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src,
                 *ldflags],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.SubprocessError):
        lib = False
    _loaded[key] = lib
    return lib
