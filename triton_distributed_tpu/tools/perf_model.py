"""Speed-of-light (SOL) performance models for GEMM and collectives.

Reference: ``python/triton_dist/tools`` perf models —
``gemm_perf_model.py:232`` (``get_tensorcore_tflops`` / DRAM roofline) and
``comm_perf_model.py:92-110`` (NVLink ring bandwidth models).  Same roles
here with TPU hardware tables: the GEMM model takes
max(MXU time, HBM time) and the collective models use the standard ring
formulas over per-chip ICI bandwidth.

Numbers are public per-chip peaks (bf16 dense MXU TFLOP/s, HBM GB/s,
aggregate ICI GB/s per chip); unknown chips fall back conservatively.
Used for "fraction of SOL" reporting in benches and the autotuner's sanity
threshold, not for correctness.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    bf16_tflops: float   # dense MXU peak
    hbm_gbps: float      # HBM bandwidth
    ici_gbps: float      # aggregate ICI bandwidth per chip (all links)


# public TPU specs (approximate board peaks); alias lists cover the real
# device_kind strings JAX reports ("TPU v5 lite" for v5e, "TPU v6 lite"/
# "TPU v6e" for v6e, ...)
_CHIPS = [
    (("v5 lite", "v5e", "v5litepod"), ChipSpec("TPU v5e", 197.0, 819.0, 186.0)),
    (("v5p", "v5"), ChipSpec("TPU v5p", 459.0, 2765.0, 536.0)),
    (("v6 lite", "v6e", "trillium"), ChipSpec("TPU v6e", 918.0, 1640.0, 230.0)),
    (("v4",), ChipSpec("TPU v4", 275.0, 1228.0, 268.0)),
]

_FALLBACK = ChipSpec("unknown", 180.0, 800.0, 180.0)

# Per-chip DCN (cross-slice) bandwidth, GB/s.  Deliberately a single
# constant, not a per-chip field: DCN is a property of the pod's NIC
# provisioning, not the chip.  Typical public multislice configurations
# land at ~12-25 GB/s per HOST = ~3-6.25 GB/s per chip (4 chips/host);
# we price the optimistic (fast) end of the per-chip share so
# DCN-relative wire wins are UNDERstated, never flattered.
DCN_GBPS_PER_CHIP = 6.25


def chip_spec(device_kind: str | None = None) -> ChipSpec:
    if device_kind is None:
        from ..core import platform

        device_kind = platform.device_kind()
    kind = device_kind.lower()
    for aliases, spec in _CHIPS:
        if any(a in kind for a in aliases):
            return spec
    return _FALLBACK


def _dtype_bytes(dtype) -> int:
    return int(jnp.dtype(dtype).itemsize)


def gemm_sol_ms(m: int, n: int, k: int, dtype=jnp.bfloat16,
                device_kind: str | None = None) -> float:
    """Roofline GEMM time: max(FLOPs / MXU peak, bytes / HBM peak)
    (reference ``get_tensorcore_tflops`` + ``estimate_gemm_sol_time_ms``).
    Flop/byte counts come from ``obs.costs`` — the same source the fused
    kernels' ``cost_estimate`` and the flight timeline read, so the
    watchdog deadline, the profiler label, and the %-of-SOL report can
    never disagree on the arithmetic."""
    from ..obs import costs

    return costs.sol_ms(costs.matmul(m, n, k, dtype, dtype), device_kind)


def dcn_gbps() -> float:
    """Per-chip DCN bandwidth: the MEASURED link calibration when one
    exists (``tools.calibrate``), else :data:`DCN_GBPS_PER_CHIP` — the
    one rate every DCN-charging consumer (two-level sol terms below,
    ``obs.costs.sol_ms``'s dcn wire term, the watchdog) reads."""
    from . import calibrate

    cal = calibrate.load_calibration()
    if cal is not None and cal.dcn_gbps:
        return float(cal.dcn_gbps)
    return float(DCN_GBPS_PER_CHIP)


def allgather_sol_ms(nbytes_per_rank: int, num_ranks: int,
                     device_kind: str | None = None) -> float:
    """Ring AG: each rank receives (n-1)/n of the gathered payload over its
    ICI links (reference ``comm_perf_model.py:92``)."""
    spec = chip_spec(device_kind)
    wire = nbytes_per_rank * (num_ranks - 1)
    return wire / (spec.ici_gbps * 1e9) * 1e3


def reduce_scatter_sol_ms(nbytes_per_rank: int, num_ranks: int,
                          device_kind: str | None = None) -> float:
    """Ring RS moves the same volume as ring AG."""
    return allgather_sol_ms(nbytes_per_rank, num_ranks, device_kind)


def allreduce_sol_ms(nbytes: int, num_ranks: int,
                     device_kind: str | None = None) -> float:
    """Two-shot (RS + AG) ring AR: 2 (n-1)/n * bytes per link."""
    spec = chip_spec(device_kind)
    wire = 2.0 * nbytes * (num_ranks - 1) / num_ranks
    return wire / (spec.ici_gbps * 1e9) * 1e3


# ---------------------------------------------------------------------------
# two-level (ICI x DCN) sol terms (ISSUE 10): the hierarchical families'
# roofline charges EACH LEVEL ITS OWN WIRE CLASS — max(ici term, dcn
# term), the perfectly-pipelined bound the scheduled launch order
# (comm.hierarchical) is built to approach.  Byte formulas are the
# per-chip accounting of ``comm.hierarchical.hier_*_wire_bytes``.


def _two_level_ms(ici_bytes: float, dcn_bytes: float,
                  device_kind: str | None = None) -> float:
    spec = chip_spec(device_kind)
    t_ici = ici_bytes / (spec.ici_gbps * 1e9)
    t_dcn = dcn_bytes / (dcn_gbps() * 1e9)
    return max(t_ici, t_dcn) * 1e3


def hier_allgather_sol_ms(nbytes_per_rank: int, n_in: int, n_out: int,
                          device_kind: str | None = None) -> float:
    """Hierarchical AG: (n_in-1) shard hops on ICI; (n_out-1) slice
    blocks of n_in shards each landing over DCN."""
    return _two_level_ms((n_in - 1) * nbytes_per_rank,
                         (n_out - 1) * n_in * nbytes_per_rank, device_kind)


def hier_reduce_scatter_sol_ms(nbytes: int, n_in: int, n_out: int,
                               device_kind: str | None = None) -> float:
    """Hierarchical RS (``nbytes`` = the per-chip partial): inner ring
    moves (n_in-1) chunks of nbytes/n_in each; psum_scatter then moves
    (n_out-1)/n_out of the 1/n_in chunk across slices."""
    chunk = nbytes / max(n_in, 1)
    return _two_level_ms((n_in - 1) * chunk,
                         (n_out - 1) * chunk / max(n_out, 1), device_kind)


def hier_allreduce_sol_ms(nbytes: int, n_in: int, n_out: int,
                          device_kind: str | None = None) -> float:
    """Hierarchical AR (RS ∘ AG): two inner rings move 2(n_in-1)/n_in of
    the partial on ICI; the DCN hop reduces only the 1/n_in partial
    (2(n_out-1)/n_out of it on the ring)."""
    return _two_level_ms(
        2.0 * nbytes * (n_in - 1) / max(n_in, 1),
        2.0 * (nbytes / max(n_in, 1)) * (n_out - 1) / max(n_out, 1),
        device_kind)


def hier_a2a_sol_ms(nbytes: int, n_in: int, n_out: int,
                    device_kind: str | None = None) -> float:
    """Scheduled EP A2A: the DCN phase ships (n_out-1) FIXED zero-padded
    payload-sized blocks per chip (static shapes — the bytes move
    regardless of routing); up to the n_out merged blocks redistribute
    on ICI."""
    return _two_level_ms(n_out * float(nbytes),
                         float(nbytes) * (n_out - 1), device_kind)


def fused_sol_ms(family: str, device_kind: str | None = None,
                 **shape_kw) -> float:
    """Roofline time of a fused kernel family via its ``obs.costs``
    calculator (``costs.FAMILY_COSTS``) — the achieved-vs-SOL denominator
    of ``scripts/obs_report.py --timeline``."""
    from ..obs import costs

    calc = costs.FAMILY_COSTS[family]
    return costs.sol_ms(calc(**shape_kw), device_kind)


def overlap_efficiency(t_measured_ms: float, t_gemm_ms: float,
                       t_comm_ms: float) -> float:
    """How much of the comm time the fused op hid:
    1.0 = fully overlapped (t == max parts), 0.0 = fully serialized
    (t == sum of parts)."""
    lo = max(t_gemm_ms, t_comm_ms)
    hi = t_gemm_ms + t_comm_ms
    if hi == lo:
        return 1.0
    return float(min(1.0, max(0.0, (hi - t_measured_ms) / (hi - lo))))
