"""Tooling: AOT compile/serialize, SOL perf models, profiling (reference:
``python/triton_dist/tools/`` + the profiling half of ``utils.py``)."""

from .aot import aot_compile, deserialize, load, save, serialize
from .perf_model import (
    ChipSpec,
    allgather_sol_ms,
    allreduce_sol_ms,
    chip_spec,
    gemm_sol_ms,
    overlap_efficiency,
    reduce_scatter_sol_ms,
)
from .profile import annotate, group_profile, memory_stats
from .trace_merge import merge_traces
