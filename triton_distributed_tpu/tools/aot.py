"""Ahead-of-time compilation and executable serialization.

Reference: the AOT toolchain — ``python/triton_dist/tools/compile_aot.py:249-470``
(C header/source generation per kernel signature) and
``csrc/triton_aot_runtime.cc`` (the hand-written loader/launcher runtime).

On TPU that entire layer collapses into XLA's own AOT path: ``.lower()``
``.compile()`` produces a serializable executable, and
``jax.experimental.serialize_executable`` replaces the generated C runtime
— the loader is ~10 lines instead of 1.7k LoC because XLA owns the launch
ABI.  What remains worth shipping is the ergonomics: compile a step once,
persist it next to the model, reload without retracing.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import jax


def aot_compile(fn: Callable | Any, *example_args, **example_kwargs):
    """Trace + compile ``fn`` (jitted or plain) for the example arguments.

    Returns the Compiled executable (callable with matching shapes).
    """
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    return fn.lower(*example_args, **example_kwargs).compile()


def serialize(compiled) -> bytes:
    """Serialize a Compiled executable (+ its in/out trees) to bytes."""
    import pickle

    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree))


def deserialize(data: bytes):
    """Rebuild a callable executable from :func:`serialize` bytes.

    Must run on a compatible device topology (same device kinds/counts) —
    the same constraint the reference's cubin loader has.  Known quirk: the
    XLA:CPU loader rebinds the executable to the full local device set, so
    on a multi-device virtual CPU platform a 1-device executable reloads
    expecting all-device sharded args; real-TPU reloads bind correctly.
    """
    import pickle

    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = pickle.loads(data)
    return se.deserialize_and_load(payload, in_tree, out_tree)


def save(compiled, path: str) -> None:
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(serialize(compiled))
    os.replace(tmp, path)


def load(path: str):
    with open(path, "rb") as f:
        return deserialize(f.read())
