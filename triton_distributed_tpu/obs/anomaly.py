"""Live-vs-baseline anomaly detection for the continuous profiler.

``obs.continuous`` rotates a window; this module compares the window's
totals against healthy bands — the ONE band implementation shared with
the trend sentinel (``obs.history.Band`` / ``healthy_band``; ISSUE 16
satellite) — and on a breach emits a typed :class:`AnomalyEvent`
carrying everything triage needs in one record:

- the window's dominant (semaphore, chunk, peer) stall triple (the
  ``obs.timeline`` attribution, already aggregated by the rollup);
- the p99 exemplar trace id (``obs.serve_stats`` sketches, TDT_TRACE —
  the "show me a p99 request" hop of docs/serving.md);
- a flight-ring excerpt (the protocol's recent history, the same tail
  a timeout dump attaches).

Default bands come from the committed bench rounds
(:func:`detector_from_rounds` -> ``history.bands_for``), so "anomalous"
means the SAME thing as a trend warning: outside the committed healthy
band by more than the slack.  Harnesses inject synthetic bands
(:class:`AnomalyDetector` takes any metric->Band dict).

Surfacing: the latest window's breaches are the WARNING state —
``resilience.health_snapshot`` attaches :func:`health_fragment` so
``health()``/``/healthz`` carry them (status stays "ok": a perf
anomaly is a warning, not a 503 — the load balancer must not shed over
drift), and the scheduler offers each anomalous window to its
AdmissionGovernor as an advisory signal (``note_advisory``: pressure
that only degrades admission if it RECURS within the governor's
window).

:func:`selftest` pins both directions: a seeded regression replay
(inflated wire payloads on a recorded capture) must be caught with the
stall triple and exemplar named; the clean replay must stay quiet.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque

from . import history

MAX_RETAINED = 32

# live window-total metrics the default detector watches, with their
# directions (derived metrics carry no unit for direction_for to sniff)
# and the committed bench-metric prefix each maps onto
WATCH = {
    "overlap_hidden_pct": ("higher", "overlap_hidden_pct"),
    "exposed_ms": ("lower", "profile_exposed_ms"),
    "pct_sol": ("higher", "profile_pct_sol"),
}

_LOCK = threading.Lock()
_EVENTS: deque = deque(maxlen=MAX_RETAINED)
_CURRENT: tuple = ()           # the LATEST window's breaches (warning state)
_TOTAL = 0
_DETECTOR: "AnomalyDetector | None" = None


@dataclasses.dataclass(frozen=True)
class AnomalyEvent:
    """One live-window band breach, typed and self-contained."""

    metric: str
    value: float
    band: tuple[float, float]
    direction: str
    drift_pct: float           # fraction past the worse band edge
    window: int
    step_end: int
    stall: tuple | None        # dominant (sem, chunk, peer, exposed_us)
    exemplar: str | None       # p99 exemplar trace id, if traced
    excerpt: tuple[str, ...]   # flight-ring tail at detection time
    # window-vs-baseline attribution (obs.diff.diff_windows against
    # the profiler's band-representative healthy window) — the
    # "why", when a baseline was available at detection time
    diff: dict | None = None

    def summary(self) -> str:
        s = (f"{self.metric}={self.value:g} outside healthy band "
             f"[{self.band[0]:g}, {self.band[1]:g}] "
             f"({100 * self.drift_pct:.1f}% worse, window "
             f"#{self.window} @ step {self.step_end})")
        if self.stall:
            sem, chunk, peer = self.stall[:3]
            s += (f"; dominant stall sem={sem} chunk={chunk} "
                  f"peer={peer}")
        if self.exemplar:
            s += f"; p99 exemplar {self.exemplar}"
        if self.diff and self.diff.get("terms"):
            s += f"; diff: {self.diff['summary']}"
        return s

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["summary"] = self.summary()
        return d


class AnomalyDetector:
    """Compares window totals against a metric->Band map (bands are
    ``obs.history.Band`` — the shared implementation).  ``record=False``
    keeps a harness run out of the process warning state."""

    def __init__(self, bands: dict[str, history.Band], *,
                 record: bool = True):
        self.bands = dict(bands)
        self.record = record

    def check_window(self, window: dict,
                     baseline: dict | None = None) -> list[AnomalyEvent]:
        """``baseline`` is the band-representative healthy window the
        profiler retains (``obs.diff.baseline_window``): when present,
        every breach carries its window-vs-baseline attribution."""
        from . import flight, serve_stats

        totals = window.get("totals") or {}
        out: list[AnomalyEvent] = []
        for metric, band in self.bands.items():
            value = totals.get(metric)
            if not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            drift = band.breach(float(value))
            if drift is None:
                continue
            exemplar = None
            for sk in (serve_stats.STATS.request_ms,
                       serve_stats.STATS.ttft_ms):
                exemplar = sk.exemplar(0.99)
                if exemplar:
                    break
            attribution = None
            if baseline is not None:
                try:
                    from . import diff as diff_mod

                    attribution = diff_mod.diff_windows(
                        baseline, window, metric=metric,
                        exemplar=exemplar)
                except Exception:
                    attribution = None
            out.append(AnomalyEvent(
                metric=metric, value=float(value),
                band=(band.lo, band.hi), direction=band.direction,
                drift_pct=drift, window=int(window.get("window", -1)),
                step_end=int(window.get("step_end", -1)),
                stall=totals.get("dominant_stall"),
                exemplar=exemplar,
                excerpt=flight.recent_lines(16),
                diff=attribution,
            ))
        if self.record:
            _publish(window, out)
        return out


def _publish(window: dict, events: list[AnomalyEvent]) -> None:
    """Retain breaches and refresh the warning state: the LATEST
    completed window defines whether health warns (an hour-old breach
    must not page forever)."""
    global _CURRENT, _TOTAL
    with _LOCK:
        _CURRENT = tuple(events)
        for e in events:
            _EVENTS.append(e)
            _TOTAL += 1


def check_window(window: dict,
                 baseline: dict | None = None) -> list[AnomalyEvent]:
    """The profiler's rotation hook: run the process detector (built
    lazily from the committed rounds) over a finished window, diffing
    breaches against ``baseline`` when the profiler retained one."""
    det = _detector()
    if det is None:
        _publish(window, [])
        return []
    return det.check_window(window, baseline)


def _detector() -> AnomalyDetector | None:
    global _DETECTOR
    if _DETECTOR is None:
        with _LOCK:
            if _DETECTOR is None:
                _DETECTOR = detector_from_rounds()
    return _DETECTOR


def set_detector(det: AnomalyDetector | None) -> None:
    """Install the process detector (harnesses; None re-derives from
    the committed rounds on next use)."""
    global _DETECTOR
    with _LOCK:
        _DETECTOR = det


def detector_from_rounds(root: str = ".") -> AnomalyDetector:
    """Bands from the committed bench rounds: each watched live metric
    maps onto the first committed trajectory matching its bench-metric
    prefix (interpret-mode rounds carry no trajectory — the detector is
    then empty and every window is healthy by definition)."""
    try:
        rounds = history.load_rounds(root)
        trs = history.trajectories(rounds)
    except OSError:
        trs = {}
    bands: dict[str, history.Band] = {}
    for live, (direction, prefix) in WATCH.items():
        names = sorted(n for n in trs if n.startswith(prefix))
        for name in names:
            tr = trs[name]
            band = history.healthy_band(tr.values, direction)
            if band is not None:
                bands[live] = band
                break
    return AnomalyDetector(bands)


# ---------------------------------------------------------------------------
# read side (health surface, /debug/profile)


def current() -> list[AnomalyEvent]:
    """The latest completed window's breaches (the warning state)."""
    return list(_CURRENT)


def recent(n: int = 8) -> list[AnomalyEvent]:
    """The newest retained breaches across windows."""
    with _LOCK:
        return list(_EVENTS)[-int(n):]


def latest_attributed() -> AnomalyEvent | None:
    """The newest retained breach that carries a window-vs-baseline
    attribution — what ``/debug/diff`` serves.  Events are frozen and
    their ``diff`` dicts are built once at detection time, so the
    returned payload is scrape-safe during window rotation."""
    with _LOCK:
        for e in reversed(_EVENTS):
            if e.diff:
                return e
    return None


def total() -> int:
    return _TOTAL


def clear() -> None:
    global _CURRENT, _TOTAL
    with _LOCK:
        _EVENTS.clear()
        _CURRENT = ()
        _TOTAL = 0


def health_fragment() -> dict | None:
    """What ``resilience.health_snapshot`` attaches under ``profile``
    when the latest window breached: a warning state — NOT a status
    flip (``/healthz`` stays 200; docs/observability.md).  None when
    healthy, so an unarmed process's snapshot is byte-identical."""
    cur = current()
    if not cur:
        return None
    return {
        "status": "warn",
        "anomalies": [e.summary() for e in cur],
        "total": _TOTAL,
    }


# ---------------------------------------------------------------------------
# selftest (tdt_lint --profile + tier-1)


def _inflate_wire(streams, factor: int):
    """The seeded regression: every remote_copy's payload inflated, so
    wire time (and the waits it starves) grows — the canonical
    'overlap got worse' shape, deterministic under the model clock."""
    import copy

    out = []
    for s in streams:
        evs = []
        for ev in s:
            e2 = copy.copy(ev)
            if ev.kind == "remote_copy":
                e2.elems = ev.elems * factor
            evs.append(e2)
        out.append(evs)
    return out


def selftest(seed: int = 0) -> list[str]:
    """Both-direction anomaly check over a REAL recorded capture run
    through the REAL profiler path: the clean replay must stay quiet;
    the regression replay (wire payloads inflated 65536x) must breach with
    the (sem, chunk, peer) stall triple and the p99 exemplar named.
    Perturbs the flight ring and serve stats; callers reset.  Returns
    problems (empty = pass)."""
    from . import continuous, flight, serve_stats

    problems: list[str] = []
    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    flight.enable(True)
    continuous.enable(True)
    try:
        # a named p99 exemplar for the event to carry — on a FRESH
        # sketch, so prior (exemplar-less) traffic cannot occupy the
        # p99 bucket (the docstring's "perturbs serve stats")
        serve_stats.STATS.reset()
        serve_stats.STATS.request_ms.observe(
            123.0, exemplar=f"req-anomaly-selftest-{seed}")
        _, streams = flight.record_family("allgather", 2)

        def window_of(streams_):
            prof = continuous.ContinuousProfiler(window_steps=1,
                                                 out_dir="")
            flight.clear()
            flight.feed_streams("allgather", streams_)
            prof.on_step("selftest", 1)
            return prof.last_window()

        healthy = window_of(streams)
        if healthy is None or not healthy["totals"]["episodes"]:
            return ["selftest: the recorded capture produced no "
                    "profiler window"]
        tot = healthy["totals"]
        bands = {}
        for metric, direction in (("exposed_ms", "lower"),
                                  ("overlap_hidden_pct", "higher")):
            v = tot[metric]
            band = history.healthy_band([v, v], direction)
            if band is not None:
                bands[metric] = band
        det = AnomalyDetector(bands, record=False)

        # direction 1: the clean replay (identical capture, identical
        # model clock) must stay quiet
        clean = det.check_window(window_of(streams))
        if clean:
            problems.append(
                f"selftest: clean replay flagged "
                f"{[e.metric for e in clean]} — identical capture must "
                f"reconstruct identically")

        # direction 2: the seeded regression must be caught
        bad = det.check_window(window_of(_inflate_wire(streams, 1 << 16)))
        if not bad:
            problems.append(
                "selftest: the 65536x wire inflation was not flagged — "
                "the live comparator is blind")
        for e in bad:
            if not e.stall or e.stall[0] is None:
                problems.append(
                    f"selftest: breach {e.metric} carries no dominant "
                    f"(sem, chunk, peer) stall triple")
            if not e.exemplar:
                problems.append(
                    f"selftest: breach {e.metric} names no p99 "
                    f"exemplar")
            if not e.excerpt:
                problems.append(
                    f"selftest: breach {e.metric} carries no "
                    f"flight-ring excerpt")
    finally:
        flight.clear()
        flight.enable(prev_flight)
        continuous.enable(prev_prof)
    return problems
