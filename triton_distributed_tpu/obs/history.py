"""Perf-trajectory analysis over the committed bench rounds.

``scripts/check_perf_claims.py`` gates the NEWEST record against
absolute floors; this module reads **all** committed rounds
(``BENCH_rNN.json`` driver envelopes and ``BENCH_LOCAL_rNN.jsonl``
complete streams) as a time series and surfaces what a single-round
floor cannot: a metric sliding toward its floor across rounds, or a
draw that cleared its floor but fell out of the healthy band the prior
rounds established.  T3's argument for continuous fine-grained overlap
tracking (arXiv 2401.16677) applied to the bench loop — drift should be
flagged *before* a floor breaks.

Per metric the trajectory sentinel reports:

- **decline** — ``decline_rounds`` (default 3) consecutive round-over-
  round moves in the worse direction whose total drift exceeds
  ``decline_pct`` (default 5% — below the chip's documented round noise
  nothing is signal).
- **below band** — the newest draw worse than every prior passing draw
  by more than ``band_slack`` (5%), where the band is the prior rounds'
  [min, max] around their median.  A draw whose symmetric retry
  (``retry_value``) is back inside the band is reported as transient,
  matching the claims gate's dip semantics.

Interpret-mode captures (functional smoke) and the sweep sentinel are
excluded from trajectories.  Direction (higher- vs lower-is-better) is
derived from the record's unit: latency-class units (``ms``/``us``)
are lower-better, throughput units higher-better, byte-accounting
units exact (no band).

The healthy-band computation itself lives in ONE place —
:func:`healthy_band` / :class:`Band` — consumed by both the trend
sentinel (:func:`analyze`) and the continuous profiler's live
comparator (``obs.anomaly``, ISSUE 16): a live window and a committed
round are judged against a band by the SAME arithmetic, so "the live
overlap fell out of band" means exactly what a trend warning means.
:func:`bands_for` is the lookup front-door (metric name -> band over
the committed rounds).  This module also parses the profiler's on-disk
time-series segments (:func:`load_profile_windows` — the JSONL window
lines ``obs.continuous`` rotates out).

Consumers: ``scripts/bench_history.py`` (the CLI, ``--json`` /
``--markdown`` / ``--check``), ``scripts/check_perf_claims.py --trend``
(trend warnings next to floor verdicts), ``scripts/tdt_lint.py
--history`` (the CI hook), ``obs.anomaly`` (the live comparator), and
``tests/test_obs.py`` fixtures.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import re

SENTINEL = "bench_sweep_complete"
DECLINE_ROUNDS = 3
DECLINE_PCT = 0.05
BAND_SLACK = 0.05
# bench.py persists the complete local stream from round 6 on (same
# constant as scripts/check_perf_claims.py): a detectably truncated
# envelope WITHOUT a local record is an inconsistent commit from there
LOCAL_RECORD_SINCE = 6

_ENVELOPE_RE = re.compile(r"BENCH_r(\d+)\.json$")
_LOCAL_RE = re.compile(r"BENCH_LOCAL_r(\d+)\.jsonl$")


@dataclasses.dataclass(frozen=True)
class Draw:
    """One metric capture in one round (interpret-mode captures are
    filtered out before Draw construction)."""

    round: int
    value: float
    unit: str
    retry_value: float | None
    source: str                # "local" | "envelope"


@dataclasses.dataclass
class Trajectory:
    metric: str
    unit: str
    direction: str             # "higher" | "lower" | "exact"
    draws: list[Draw]
    band: tuple[float, float] | None = None   # prior-round [lo, hi]
    warnings: list[str] = dataclasses.field(default_factory=list)

    @property
    def values(self) -> list[float]:
        return [d.value for d in self.draws]


def parse_record_text(text: str) -> tuple[list[dict], int | None, bool]:
    """(metric lines, envelope rc, truncation detected) — the same
    envelope-or-raw-JSONL shape ``scripts/check_perf_claims.py`` parses
    (reimplemented here because the package must not import scripts)."""
    metrics: list[dict] = []
    rc = None
    truncated = False
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and "tail" in obj:
            rc = obj.get("rc")
            text = obj["tail"]
            nonempty = [ln for ln in text.splitlines() if ln.strip()]
            truncated = bool(nonempty) and \
                not nonempty[0].lstrip().startswith("{")
    except ValueError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            metrics.append(rec)
    return metrics, rc, truncated


@dataclasses.dataclass
class Round:
    """One committed round's parsed record(s)."""

    round: int
    metrics: list[dict]
    source: str                # "local" | "envelope"
    rc: int | None
    truncated: bool
    envelope_metrics: list[dict] | None = None  # when both exist


def load_rounds(root: str) -> list[Round]:
    """All committed rounds, ascending; a round with BOTH a local stream
    and an envelope prefers the local record (complete by construction)
    and keeps the envelope lines for the consistency check."""
    env: dict[int, str] = {}
    loc: dict[int, str] = {}
    for pat, rx, sink in ((os.path.join(root, "BENCH_r*.json"),
                           _ENVELOPE_RE, env),
                          (os.path.join(root, "BENCH_LOCAL_r*.jsonl"),
                           _LOCAL_RE, loc)):
        for p in glob.glob(pat):
            m = rx.search(p)
            if m:
                sink[int(m.group(1))] = p
    rounds: list[Round] = []
    for rnd in sorted(set(env) | set(loc)):
        env_metrics = rc = None
        truncated = False
        if rnd in env:
            with open(env[rnd]) as f:
                env_metrics, rc, truncated = parse_record_text(f.read())
        if rnd in loc:
            with open(loc[rnd]) as f:
                metrics, _, _ = parse_record_text(f.read())
            rounds.append(Round(rnd, metrics, "local", rc, truncated,
                                envelope_metrics=env_metrics))
        else:
            rounds.append(Round(rnd, env_metrics or [], "envelope", rc,
                                truncated))
    return rounds


_PROFILE_SEGMENT_RE = re.compile(r"profile_(\d+)\.jsonl$")
_DECISION_SEGMENT_RE = re.compile(r"decisions_(\d+)\.jsonl$")


def load_profile_windows(dirpath: str) -> list[dict]:
    """Parse the continuous profiler's on-disk time-series segments
    (``obs.continuous`` writes one JSONL line per rotated window into
    ``profile_NNNN.jsonl`` segments under ``TDT_PROFILE_DIR``).
    Returns the window dicts in rotation order — ascending (segment,
    line) — skipping unparseable lines (a segment truncated by rotation
    mid-write must not turn analysis into a crash)."""
    paths = []
    for p in glob.glob(os.path.join(dirpath, "profile_*.jsonl")):
        m = _PROFILE_SEGMENT_RE.search(p)
        if m:
            paths.append((int(m.group(1)), p))
    out: list[dict] = []
    for _, p in sorted(paths):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "window" in rec:
                    out.append(rec)
    return out


def load_decision_records(dirpath: str) -> list[dict]:
    """Parse the control-decision ledger's on-disk time-series
    (``obs.decisions`` writes one JSONL line per record into
    ``decisions_NNNN.jsonl`` segments under ``TDT_DECISION_DIR``, the
    profiler's rotation discipline).  Returns the record dicts in
    ledger order — ascending (segment, line) — skipping unparseable
    lines exactly like :func:`load_profile_windows`."""
    paths = []
    for p in glob.glob(os.path.join(dirpath, "decisions_*.jsonl")):
        m = _DECISION_SEGMENT_RE.search(p)
        if m:
            paths.append((int(m.group(1)), p))
    out: list[dict] = []
    for _, p in sorted(paths):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "kind" in rec:
                    out.append(rec)
    return out


def profile_series(windows: list[dict], metric: str) -> list[float]:
    """One window-total metric as a time series (the per-window
    ``totals`` dict of :func:`load_profile_windows` records)."""
    out = []
    for w in windows:
        v = (w.get("totals") or {}).get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and math.isfinite(float(v)):
            out.append(float(v))
    return out


# Ordered (rule_id, direction, predicate) rows — THE trend-direction
# classification, exported as a golden table so
# analysis/completeness.py::check_direction_coverage can pin it both
# ways: every metric bench.py emits must classify under a named rule
# (metrics riding the catch-all must be listed in completeness.py's
# DEFAULT_HIGHER_OK golden set), and a rule no emitted metric
# exercises is flagged dead.  First match wins.
DIRECTION_RULES: tuple = (
    # value equality is the contract (bench_sweep_complete "bool",
    # moe_ep_a2a_fp8_wire_bytes "bytes/token/hop")
    ("exact-unit", "exact",
     lambda m, u: "bytes/token" in u or u == "bool"),
    # wall-clock latencies
    ("latency-unit", "lower",
     lambda m, u: u.startswith("ms") or u.startswith("us")
     or "ms/" in u or m.startswith("latency")),
    # cost/tax metrics (integrity_overhead_pct "% over plain",
    # trace_overhead_pct "% over untraced" — ISSUE 14): growth is the
    # regression the sentinel must warn on
    ("overhead-tax", "lower",
     lambda m, u: "overhead" in m or "over plain" in u
     or "over untraced" in u),
    # per-bundle dispatch counts (decode_dispatches_per_bundle, unit
    # "dispatches/bundle"): every extra launch is a host seam the
    # persistent loop exists to remove — growth is the regression.
    # (The older decode_step_dispatches metric is a HIGHER-is-better
    # ratio, unit "x fewer dispatches", and keeps the default.)
    ("dispatch-count", "lower", lambda m, u: "dispatches/" in u),
    # failure-pressure counts (handoff_retries, *_failures, *_failed_*):
    # every one is a burned retry/ladder rung or a lost request — growth
    # is the regression even though the unit is a bare count (ISSUE 12;
    # handoff_ms_p99 and serve_disagg_ttft_ms_p99 ride the ms rule
    # above, handoff_pages_per_s the throughput default below)
    ("failure-pressure", "lower",
     lambda m, u: any(tok in m for tok in ("retries", "failures",
                                           "failed"))),
    # convergence latencies in scheduler steps (fleet_rebalance_
    # convergence_steps — ISSUE 18): every extra step is load served by
    # the wrong membership — growth is the regression (fleet_ttft_ms_
    # p99_under_loss rides the ms rule above)
    ("convergence-steps", "lower",
     lambda m, u: u == "steps" or "convergence" in m),
    # fleet-obs control-plane health (ISSUE 19): a rising decision
    # RATE means the controller is actuating more (sheds, failovers,
    # quarantine walks — a healthy fleet routes and little else), and
    # rising same-role SKEW or occupancy SPREAD means the balancer is
    # losing — growth is the regression for all three.  Federation
    # merge counts (fleet_requests_*, fleet_tokens_*) keep the
    # throughput default below.
    ("control-plane-pressure", "lower",
     lambda m, u: any(tok in m for tok in ("decision_rate", "skew",
                                           "spread"))),
    # the deliberate catch-all: rates/ratios where more is better
    # (TFLOP/s, tok/s, pages/s, hidden-overlap fractions)
    ("throughput-default", "higher", lambda m, u: True),
)


def classify_direction(metric: str, unit: str) -> tuple[str, str]:
    """``(rule_id, direction)`` under the golden table — the ONE
    classification; :func:`direction_for` delegates here."""
    u = (unit or "").lower()
    for rule_id, direction, pred in DIRECTION_RULES:
        if pred(metric, u):
            return rule_id, direction
    return "throughput-default", "higher"   # unreachable: catch-all


def direction_for(metric: str, unit: str) -> str:
    return classify_direction(metric, unit)[1]


def trajectories(rounds: list[Round]) -> dict[str, Trajectory]:
    """Per-metric draws across rounds, oldest first.  Sentinel lines,
    interpret captures, and non-numeric values are excluded."""
    out: dict[str, Trajectory] = {}
    for rnd in rounds:
        for rec in rnd.metrics:
            name = rec.get("metric")
            value = rec.get("value")
            if (not name or name == SENTINEL
                    or not isinstance(value, (int, float))
                    or isinstance(value, bool)
                    or not math.isfinite(float(value))
                    or rec.get("interpret")):
                continue
            unit = str(rec.get("unit", ""))
            tr = out.get(name)
            if tr is None:
                tr = out[name] = Trajectory(
                    name, unit, direction_for(name, unit), [])
            retry = rec.get("retry_value")
            tr.draws.append(Draw(
                rnd.round, float(value), unit,
                float(retry) if isinstance(retry, (int, float)) else None,
                rnd.source,
            ))
    return out


def _worse(direction: str, a: float, b: float) -> bool:
    """Whether ``a`` is worse than ``b``."""
    return a < b if direction == "higher" else a > b


def _drift_pct(direction: str, newest: float, ref: float) -> float:
    if ref == 0:
        return 0.0
    d = (ref - newest) / abs(ref) if direction == "higher" \
        else (newest - ref) / abs(ref)
    return d


@dataclasses.dataclass(frozen=True)
class Band:
    """A healthy band: the draws' [min, max] around their median, with
    a slack margin before a value outside it counts as a breach.  The
    ONE band shape both the trend sentinel and the live comparator
    (``obs.anomaly``) judge against."""

    lo: float
    hi: float
    median: float
    direction: str             # "higher" | "lower"
    slack: float = BAND_SLACK

    @property
    def edge(self) -> float:
        """The band boundary on the WORSE side."""
        return self.lo if self.direction == "higher" else self.hi

    def breach(self, value: float) -> float | None:
        """Drift (fraction) past the worse edge when ``value`` falls
        out of band by more than ``slack``; ``None`` when healthy.
        Exactly the :func:`analyze` below-band predicate."""
        if not _worse(self.direction, float(value), self.edge):
            return None
        d = _drift_pct(self.direction, float(value), self.edge)
        return d if d > self.slack else None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def healthy_band(values, direction: str, *,
                 slack: float = BAND_SLACK) -> Band | None:
    """THE healthy-band computation (one implementation, two consumers:
    :func:`analyze`'s below-band check and ``obs.anomaly``'s live
    comparator).  ``None`` when there is no band to speak of: fewer
    than two draws (one point has no spread) or an exact-direction
    metric."""
    vals = [float(v) for v in values]
    if direction == "exact" or len(vals) < 2:
        return None
    med = sorted(vals)[len(vals) // 2]
    return Band(min(vals), max(vals), med, direction, slack)


def bands_for(metric: str, *, rounds: list[Round] | None = None,
              root: str = ".",
              band_slack: float = BAND_SLACK) -> Band | None:
    """The band lookup front-door: the healthy band of ``metric`` over
    ALL committed draws (they are all prior rounds relative to a live
    window).  ``None`` when the metric has no committed trajectory or
    too few draws for a band."""
    if rounds is None:
        rounds = load_rounds(root)
    tr = trajectories(rounds).get(metric)
    if tr is None:
        return None
    return healthy_band(tr.values, tr.direction, slack=band_slack)


def analyze(rounds: list[Round], *, decline_rounds: int = DECLINE_ROUNDS,
            decline_pct: float = DECLINE_PCT,
            band_slack: float = BAND_SLACK) -> dict[str, Trajectory]:
    """Trajectories with healthy bands and WARN annotations attached."""
    trs = trajectories(rounds)
    for tr in trs.values():
        if tr.direction == "exact" or len(tr.draws) < 2:
            continue
        vals = tr.values
        newest = tr.draws[-1]
        prior = vals[:-1]
        tr.band = (min(prior), max(prior))
        # -- N-round monotonic decline ---------------------------------
        if len(vals) >= decline_rounds + 1:
            tail = vals[-(decline_rounds + 1):]
            monotone = all(_worse(tr.direction, tail[i + 1], tail[i])
                           for i in range(len(tail) - 1))
            drift = _drift_pct(tr.direction, tail[-1], tail[0])
            if monotone and drift > decline_pct:
                tr.warnings.append(
                    f"{tr.metric}: {decline_rounds}-round monotonic "
                    f"decline — {tail[0]:g} -> {tail[-1]:g} {tr.unit} "
                    f"({100 * drift:.1f}% worse over rounds "
                    f"r{tr.draws[-decline_rounds - 1].round:02d}.."
                    f"r{newest.round:02d})")
        # -- newest draw below the prior healthy band ------------------
        # (healthy_band returns None under two prior rounds: one draw
        # has no spread, and a "band" of one point would flag ordinary
        # round noise)
        band = healthy_band(prior, tr.direction, slack=band_slack)
        if band is None:
            continue
        lo, hi, med = band.lo, band.hi, band.median
        if band.breach(newest.value) is not None:
            retry_ok = newest.retry_value is not None and not _worse(
                tr.direction, newest.retry_value, band.edge)
            if retry_ok:
                tr.warnings.append(
                    f"{tr.metric}: r{newest.round:02d} draw "
                    f"{newest.value:g} {tr.unit} fell below the prior "
                    f"band [{lo:g}, {hi:g}] but its retry "
                    f"({newest.retry_value:g}) is back inside — "
                    f"transient throttle, watch the next round")
            else:
                tr.warnings.append(
                    f"{tr.metric}: r{newest.round:02d} draw "
                    f"{newest.value:g} {tr.unit} is outside the prior "
                    f"rounds' healthy band [{lo:g}, {hi:g}] (median "
                    f"{med:g}) — above any floor, but the trajectory "
                    f"regressed")
    # regression forensics (obs.diff): a WARN line should be an
    # explanation candidate, not just a flag — append the
    # round-over-round co-movement note so bench_history and
    # check_perf_claims --trend carry their first causal lead inline
    for tr in trs.values():
        if not tr.warnings:
            continue
        try:
            from . import diff as _diff

            note = _diff.rounds_attribution(trs, tr.metric)
        except Exception:
            note = None
        if note:
            tr.warnings[:] = [w + note for w in tr.warnings]
    return trs


def consistency_problems(rounds: list[Round]) -> list[str]:
    """Hard internal-consistency failures of the committed records (the
    ``--check`` teeth): a locally-teed round disagreeing with its
    same-round envelope on a shared metric value, a local (complete by
    construction) record missing a metric its own sentinel lists as
    emitted, a crashed sweep (rc != 0 / sentinel value 0), or a record
    with no parseable metric lines at all."""
    problems: list[str] = []
    for rnd in rounds:
        tag = f"r{rnd.round:02d}"
        if not rnd.metrics:
            problems.append(f"{tag}: no metric lines parsed from the "
                            f"committed record")
            continue
        if rnd.rc not in (None, 0):
            problems.append(f"{tag}: driver envelope records bench exit "
                            f"code {rnd.rc} — the sweep crashed")
        if (rnd.truncated and rnd.source == "envelope"
                and rnd.round >= LOCAL_RECORD_SINCE):
            # pre-round-6 envelopes never had a local record to fall
            # back on (the claims gate's legacy-warning class); from
            # round 6 the complete stream provably existed on disk
            problems.append(
                f"{tag}: envelope tail is detectably truncated and no "
                f"BENCH_LOCAL_r{rnd.round:02d}.jsonl is committed — "
                f"trajectory draws for this round are incomplete")
        sentinel = next((r for r in rnd.metrics
                         if r.get("metric") == SENTINEL), None)
        if sentinel is not None and not sentinel.get("value"):
            problems.append(f"{tag}: {SENTINEL}=0 — a bench mode crashed "
                            f"mid-sweep")
        # round-id stamp (bench.py stamps every line since round 6): a
        # record whose lines claim another round was renamed or mixed
        # from a different capture
        for rec in rnd.metrics:
            stamp = rec.get("round")
            if isinstance(stamp, int) and stamp != rnd.round:
                problems.append(
                    f"{tag}: metric {rec.get('metric')!r} is stamped "
                    f"round={stamp} but committed as round {rnd.round} — "
                    f"the record file was renamed or mixed from another "
                    f"capture")
                break
        # a local stream is complete by construction: every name its own
        # sentinel lists must be present as a line
        if rnd.source == "local" and sentinel is not None:
            have = {r.get("metric") for r in rnd.metrics}
            for name in sentinel.get("emitted") or []:
                if name not in have:
                    problems.append(
                        f"{tag}: local record's sentinel lists "
                        f"{name!r} as emitted but the line is missing — "
                        f"the stream is internally inconsistent")
        # local vs same-round envelope: the tee and the stdout tail are
        # the same bytes; a differing value means one record was edited
        # or mixed from another run
        if rnd.envelope_metrics:
            env_by = {r["metric"]: r for r in rnd.envelope_metrics
                      if "metric" in r}
            for rec in rnd.metrics:
                name = rec.get("metric")
                other = env_by.get(name)
                if other is None or name == SENTINEL:
                    continue
                if rec.get("value") != other.get("value"):
                    problems.append(
                        f"{tag}: metric {name!r} disagrees between the "
                        f"local record ({rec.get('value')!r}) and the "
                        f"driver envelope ({other.get('value')!r}) — "
                        f"the committed records are not one capture")
    return problems


def all_warnings(trs: dict[str, Trajectory]) -> list[str]:
    out: list[str] = []
    for name in sorted(trs):
        out.extend(trs[name].warnings)
    return out


# ---------------------------------------------------------------------------
# rendering


def _fmt_band(tr: Trajectory) -> str:
    if tr.band is None:
        return "-"
    return f"[{tr.band[0]:g}, {tr.band[1]:g}]"


def format_table(trs: dict[str, Trajectory]) -> str:
    """Aligned per-metric trajectory table (the operator view)."""
    if not trs:
        return "(no committed bench rounds found)\n"
    header = ("metric", "unit", "dir", "draws (oldest..newest)",
              "prior band", "status")
    rows = [header]
    for name in sorted(trs):
        tr = trs[name]
        draws = " ".join(f"r{d.round:02d}:{d.value:g}" for d in tr.draws)
        status = "WARN" if tr.warnings else "ok"
        rows.append((tr.metric, tr.unit, tr.direction, draws,
                     _fmt_band(tr), status))
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(w)
                               for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    warns = all_warnings(trs)
    if warns:
        lines.append("")
        for w in warns:
            lines.append(f"WARN {w}")
    return "\n".join(lines) + "\n"


def format_markdown(trs: dict[str, Trajectory]) -> str:
    lines = ["| metric | unit | dir | draws | prior band | status |",
             "|---|---|---|---|---|---|"]
    for name in sorted(trs):
        tr = trs[name]
        draws = ", ".join(f"r{d.round:02d}: {d.value:g}"
                          for d in tr.draws)
        status = "**WARN**" if tr.warnings else "ok"
        lines.append(f"| `{tr.metric}` | {tr.unit} | {tr.direction} | "
                     f"{draws} | {_fmt_band(tr)} | {status} |")
    for w in all_warnings(trs):
        lines.append(f"- WARN: {w}")
    return "\n".join(lines) + "\n"


def to_json(trs: dict[str, Trajectory],
            problems: list[str] | None = None) -> dict:
    return {
        "metrics": {
            name: {
                "unit": tr.unit,
                "direction": tr.direction,
                "draws": [dataclasses.asdict(d) for d in tr.draws],
                "band": list(tr.band) if tr.band else None,
                "warnings": tr.warnings,
            }
            for name, tr in sorted(trs.items())
        },
        "warnings": all_warnings(trs),
        "problems": problems or [],
    }
