"""Continuous overlap profiler: always-on streaming SOL / exposed-wait
attribution (``TDT_PROFILE=1``).

The stack can attribute overlap *offline* (``obs.timeline`` over flight
captures) and trend *committed bench rounds* (``obs.history``); this
module is the always-on bridge: at every scheduler step boundary
(``serve.Scheduler._step_impl`` calls :func:`on_step`) the profiler
drains the flight ring **incrementally** — an identity cursor on the
last-consumed event; never a re-reconstruction of the whole retained
ring — and folds the new events into windowed per-(collective family x
topology x tier) rollups:

- ``overlap_hidden_pct`` — how much of the wire time compute hid
  (``100 * (1 - exposed/wire)``, clamped to [0, 100]);
- ``exposed_ms`` — the attributed stall total;
- ``pct_sol`` — reconstructed critical path vs the ``obs.costs`` /
  ``tools.perf_model`` roofline (``Timeline.pct_sol``);
- straggler ``skew_us`` and the dominant (semaphore, chunk, peer)
  stall triple.

Attribution runs the SAME credit replay as the offline reconstructor
(``obs.timeline.reconstruct``) over each drained episode — a marker-
delimited run of events: a ``collective`` marker (``flight.
mark_collective`` / ``flight.feed_streams``) opens an episode and names
its family; rank >= 0 events group into per-rank streams; live rank −1
primitives form one stream; a marker with no primitives still counts
(episode + wire bytes).  Because the arithmetic is shared, the live
rollups AGREE with ``obs_report.py --timeline`` on the same capture —
pinned by test.

Every ``TDT_PROFILE_WINDOW`` (default 32) step-boundary drains the
open window rotates: an immutable summary dict is published (readers
never see a torn window), per-window totals feed rotating
``obs.serve_stats`` quantile sketches and gauges, one JSONL line is
appended to the bounded on-disk time-series (``TDT_PROFILE_DIR``:
``profile_NNNN.jsonl`` segments, size-rotated, oldest deleted —
``obs.history.load_profile_windows`` parses them back), and the window
is handed to ``obs.anomaly`` for the live-vs-baseline comparison
(breaches surface in ``health()`` and nudge the AdmissionGovernor).

Exported via ``/metrics`` (:func:`to_prometheus`), ``/debug/profile``
(:meth:`ContinuousProfiler.snapshot`), and ``scripts/obs_report.py
--live``.  The TDT_OBS discipline holds: unset, the scheduler hook is
one cached-bool check and behavior is byte-identical.
"""

from __future__ import annotations

import json
import os
import threading

from . import serve_stats

DEFAULT_WINDOW_STEPS = 32
# rotated windows retained in memory as baseline candidates for the
# window-vs-baseline attribution (obs.diff.baseline_window picks the
# band-representative healthy one)
RECENT_WINDOWS = 8
# calibration-drift sentinel (ISSUE 20 satellite): achieved wire GB/s
# per wire class (rollup wire_bytes / wire time) vs the persisted
# LinkCalibration rate SOL attribution assumes.  Divergence past
# LINKCAL_DRIFT_PCT for LINKCAL_SUSTAIN consecutive windows marks the
# wire class stale — a /healthz WARNING (never a 503), because a rotten
# rate silently corrupts every pct_sol number downstream.
LINKCAL_DRIFT_PCT = 0.20
LINKCAL_SUSTAIN = 3
# on-disk time-series bounds: segments rotate at this size, oldest
# segments beyond the cap are deleted — the series is downsampled (one
# line per window) AND bounded (docs/observability.md)
SEGMENT_MAX_BYTES = 256 * 1024
MAX_SEGMENTS = 8

# flight-event kinds the credit replay consumes (timeline.reconstruct
# filters the rest); a marker-only episode has none of these
_PRIM_KINDS = frozenset((
    "wait", "notify", "remote_copy", "local_copy", "wait_recv",
    "wait_send", "barrier", "compute",
))


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_PROFILE")


# Cached so a disabled scheduler step pays one global load + one bool
# check (the TDT_OBS discipline); re-read the env via enable(None).
_ENABLED = _env_enabled()

_LOCK = threading.Lock()
_PROFILER: "ContinuousProfiler | None" = None

_pkg_cache: list = []


def _suppressed() -> bool:
    """Honor ``obs.suppress()``: warmup / measurement-only steps must
    not pollute the live windows (same marker the flight ring honors)."""
    if not _pkg_cache:
        import sys

        _pkg_cache.append(sys.modules[__package__])
    return _pkg_cache[0]._suppressed()


def enabled() -> bool:
    """Whether the profiler records (``TDT_PROFILE=1`` or
    :func:`enable`, and not inside an ``obs.suppress()`` block)."""
    return _ENABLED and not _suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn the profiler on/off; ``None`` re-reads ``TDT_PROFILE``."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


def window_steps() -> int:
    """Window length in scheduler step boundaries
    (``TDT_PROFILE_WINDOW``, default 32)."""
    try:
        return max(1, int(os.environ.get("TDT_PROFILE_WINDOW", "")
                          or DEFAULT_WINDOW_STEPS))
    except ValueError:
        return DEFAULT_WINDOW_STEPS


def profile_dir() -> str | None:
    """Where the time-series segments land (``TDT_PROFILE_DIR``); None
    disables persistence (in-memory windows only)."""
    return os.environ.get("TDT_PROFILE_DIR", "").strip() or None


def _clamp(v: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, v))


class Rollup:
    """Accumulated attribution for one (family, topology, tier) key —
    within the open window, and cumulatively over the profiler's
    lifetime (the coverage view ``tdt_lint --profile`` asserts on)."""

    __slots__ = ("family", "topology", "tier", "episodes", "events",
                 "compute_us", "wire_us", "exposed_us", "barrier_us",
                 "critical_us", "sol_us", "skew_us", "wire_bytes",
                 "stalls", "pending")

    def __init__(self, family: str, topology: str, tier: str):
        self.family = family
        self.topology = topology
        self.tier = tier
        self.episodes = 0
        self.events = 0
        self.compute_us = 0.0
        self.wire_us = 0.0
        self.exposed_us = 0.0
        self.barrier_us = 0.0
        self.critical_us = 0.0
        self.sol_us = 0.0
        self.skew_us = 0.0
        self.wire_bytes = 0
        # (sem, chunk, peer) -> exposed_us: the attribution triples
        self.stalls: dict[tuple, float] = {}
        self.pending = 0

    def add_timeline(self, tl, n_events: int) -> None:
        """Fold one reconstructed episode in — the SAME sums the
        offline table prints, so live == offline on a shared capture."""
        self.episodes += 1
        self.events += n_events
        self.compute_us += sum(rw.compute_us for rw in tl.rows)
        self.wire_us += sum(rw.wire_us for rw in tl.rows)
        self.exposed_us += sum(rw.exposed_us for rw in tl.rows)
        self.barrier_us += sum(rw.barrier_us for rw in tl.rows)
        self.critical_us += tl.critical_us
        self.sol_us += tl.sol_us
        self.skew_us = max(self.skew_us, tl.skew_us)
        self.pending += len(tl.pending)
        for w in tl.waits:
            key = (w.sem, w.chunk, w.source)
            self.stalls[key] = self.stalls.get(key, 0.0) + w.exposed_us

    def add_marker(self, nbytes: int) -> None:
        """A host-dispatch marker with no primitive events: the episode
        still counts (live comm traffic is legible even when no record-
        mode stream rides along)."""
        self.episodes += 1
        self.events += 1
        self.wire_bytes += int(nbytes)

    @property
    def overlap_hidden_pct(self) -> float:
        """How much of the wire time the compute/protocol hid.  All
        hidden (vacuously) when there is no wire time."""
        if self.wire_us <= 0:
            return 100.0
        return _clamp(100.0 * (1.0 - self.exposed_us / self.wire_us),
                      0.0, 100.0)

    @property
    def pct_sol(self) -> float:
        """Roofline-vs-critical-path, the ``Timeline.pct_sol`` figure
        summed over the window's episodes."""
        if self.critical_us <= 0:
            return 1.0
        return min(1.0, self.sol_us / self.critical_us)

    def dominant_stall(self) -> tuple | None:
        """The (sem, chunk, peer) triple with the largest attributed
        exposed-wait in this rollup, with its total."""
        if not self.stalls:
            return None
        key = max(self.stalls, key=lambda k: self.stalls[k])
        return (*key, round(self.stalls[key], 3))

    def merge(self, other: "Rollup") -> None:
        self.episodes += other.episodes
        self.events += other.events
        self.compute_us += other.compute_us
        self.wire_us += other.wire_us
        self.exposed_us += other.exposed_us
        self.barrier_us += other.barrier_us
        self.critical_us += other.critical_us
        self.sol_us += other.sol_us
        self.skew_us = max(self.skew_us, other.skew_us)
        self.wire_bytes += other.wire_bytes
        self.pending += other.pending
        for k, v in other.stalls.items():
            self.stalls[k] = self.stalls.get(k, 0.0) + v

    def to_dict(self) -> dict:
        return {
            "family": self.family,
            "topology": self.topology,
            "tier": self.tier,
            "episodes": self.episodes,
            "events": self.events,
            "compute_us": round(self.compute_us, 3),
            "wire_us": round(self.wire_us, 3),
            "exposed_us": round(self.exposed_us, 3),
            "barrier_us": round(self.barrier_us, 3),
            "critical_us": round(self.critical_us, 3),
            "sol_us": round(self.sol_us, 3),
            "skew_us": round(self.skew_us, 3),
            "wire_bytes": self.wire_bytes,
            "overlap_hidden_pct": round(self.overlap_hidden_pct, 3),
            "pct_sol": round(self.pct_sol, 4),
            "dominant_stall": self.dominant_stall(),
            "pending": self.pending,
        }


def _split_episodes(events):
    """Marker-delimited episode split of a drained batch: a
    ``collective`` event opens an episode named by its ``op`` (the
    family); ``step`` marks close without opening.  Yields
    ``(family | None, [events])`` — family None means raw primitive
    traffic with no marker (attributed as "unattributed")."""
    episodes: list[tuple[str | None, list]] = []
    fam: str | None = None
    cur: list = []
    for ev in events:
        if ev.kind == "collective":
            if cur:
                episodes.append((fam, cur))
            fam, cur = ev.op, [ev]
        elif ev.kind == "step":
            if cur:
                episodes.append((fam, cur))
            fam, cur = None, []
        else:
            cur.append(ev)
    if cur:
        episodes.append((fam, cur))
    return episodes


class ContinuousProfiler:
    """The streaming profiler state machine (one per process under the
    module singleton; harnesses may install their own via
    :func:`install`).  All mutation happens under one lock; the last
    rotated window is published as an immutable dict so concurrent
    ``/metrics`` / ``/debug/profile`` scrapes never see a torn
    snapshot."""

    def __init__(self, *, window_steps: int | None = None,
                 out_dir: str | None = None,
                 device_kind: str | None = None):
        self.window_steps = int(window_steps) if window_steps \
            else globals()["window_steps"]()
        self.out_dir = out_dir if out_dir is not None else profile_dir()
        self.device_kind = device_kind
        self._lock = threading.RLock()
        self._last_ev = None            # identity cursor into the ring
        self._accum: dict[tuple, Rollup] = {}
        self._lifetime: dict[tuple, Rollup] = {}
        self._window_id = 0
        self._steps_in_window = 0
        self._last_window: dict | None = None
        self.windows_total = 0
        self.anomalies_total = 0
        # rotating per-window sketches (the serve_stats substrate):
        # exposed-wait and hidden-overlap distributions across windows
        self.exposed_ms_sketch = serve_stats.QuantileSketch()
        self.overlap_sketch = serve_stats.QuantileSketch()
        self._segment_idx = 0
        self._segment_path: str | None = None
        # baseline candidates for the window-vs-baseline diff (the
        # published dicts are immutable, so retaining references is
        # scrape-safe) and the calibration-drift streaks per wire class
        from collections import deque

        self._recent_windows: deque = deque(maxlen=RECENT_WINDOWS)
        self._linkcal_streak: dict[str, int] = {}
        self._linkcal_stale: dict[str, dict] = {}

    # -- drain -------------------------------------------------------------

    def _drain(self) -> list:
        """New flight-ring events since the last drain.  The cursor is
        the identity of the last consumed event: pruning only removes
        from the ring's LEFT (oldest), so when the cursor is gone every
        retained event is newer — O(new events), never a rescan of the
        whole ring."""
        from . import flight

        ring = flight._ring
        last = self._last_ev
        out: list = []
        try:
            for ev in reversed(ring):
                if ev is last:
                    break
                out.append(ev)
        except RuntimeError:
            # the deque mutated under a lock-free append mid-iteration:
            # fall back to a snapshot copy for this drain
            evs = list(ring)
            out = []
            for ev in reversed(evs):
                if ev is last:
                    break
                out.append(ev)
        out.reverse()
        if out:
            self._last_ev = out[-1]
        return out

    # -- ingest ------------------------------------------------------------

    def _rollup(self, sink: dict, family: str, topology: str,
                tier: str) -> Rollup:
        key = (family, topology, tier)
        r = sink.get(key)
        if r is None:
            r = sink[key] = Rollup(family, topology, tier)
        return r

    def _ingest(self, events, tier: str) -> None:
        from . import timeline

        for fam, evs in _split_episodes(events):
            marker = next((e for e in evs if e.kind == "collective"),
                          None)
            family = fam or "unattributed"
            ranks = sorted({e.rank for e in evs if e.rank >= 0})
            if ranks:
                streams = [[e for e in evs if e.rank == r]
                           for r in ranks]
                topology = f"n{len(ranks)}"
            else:
                prims = [e for e in evs if e.kind in _PRIM_KINDS]
                if not prims:
                    if marker is None:
                        continue
                    topology = f"n{marker.elems}" if marker.elems \
                        else "live"
                    for sink in (self._accum, self._lifetime):
                        self._rollup(sink, family, topology,
                                     tier).add_marker(marker.bytes)
                    continue
                streams = [prims]
                topology = f"n{marker.elems}" \
                    if marker is not None and marker.elems else "live"
            tl = timeline.reconstruct(streams, kernel=family,
                                      device_kind=self.device_kind)
            n_events = sum(len(s) for s in streams)
            for sink in (self._accum, self._lifetime):
                self._rollup(sink, family, topology,
                             tier).add_timeline(tl, n_events)

    # -- the scheduler hook ------------------------------------------------

    def on_step(self, tier: str, step: int, governor=None) -> None:
        """One step boundary: drain, ingest, maybe rotate."""
        with self._lock:
            new = self._drain()
            if new:
                self._ingest(new, tier)
            self._steps_in_window += 1
            if self._steps_in_window >= self.window_steps:
                self._rotate(step, governor)

    # -- rotation ----------------------------------------------------------

    def _totals(self, rollups) -> dict:
        tot = Rollup("_totals", "-", "-")
        for r in rollups:
            tot.merge(r)
        return {
            "episodes": tot.episodes,
            "events": tot.events,
            "exposed_ms": round(tot.exposed_us / 1e3, 6),
            "wire_ms": round(tot.wire_us / 1e3, 6),
            "compute_ms": round(tot.compute_us / 1e3, 6),
            "overlap_hidden_pct": round(tot.overlap_hidden_pct, 3),
            "pct_sol": round(tot.pct_sol, 4),
            "skew_us": round(tot.skew_us, 3),
            "wire_bytes": tot.wire_bytes,
            "dominant_stall": tot.dominant_stall(),
        }

    def _rotate(self, step: int, governor=None) -> None:
        rollups = list(self._accum.values())
        window = {
            "window": self._window_id,
            "step_end": int(step),
            "steps": self._steps_in_window,
            "window_steps": self.window_steps,
            "rollups": [r.to_dict() for r in rollups],
            "totals": self._totals(rollups),
        }
        tot = window["totals"]
        self.exposed_ms_sketch.observe(tot["exposed_ms"])
        self.overlap_sketch.observe(tot["overlap_hidden_pct"])
        # live gauges beside the serve block in /metrics (rendered
        # `serve_profile_*` by ServeStats) — last-window values
        stats = serve_stats.STATS
        stats.set_gauge("profile_overlap_hidden_pct",
                        tot["overlap_hidden_pct"])
        stats.set_gauge("profile_exposed_ms", tot["exposed_ms"])
        stats.set_gauge("profile_windows", float(self.windows_total + 1))
        self._persist(window)
        try:
            self._check_calibration(rollups)
        except Exception:
            pass
        # live-vs-baseline comparison (obs.anomaly): breaches carry the
        # dominant stall triple + p99 exemplar + ring excerpt, AND the
        # window-vs-baseline attribution (obs.diff) against the
        # band-representative healthy window retained below; they
        # surface in health() and nudge the AdmissionGovernor (advisory)
        try:
            from . import anomaly, diff

            baseline = diff.baseline_window(list(self._recent_windows))
            events = anomaly.check_window(window, baseline)
        except Exception:
            events = []
        if events:
            window["anomalies"] = [e.summary() for e in events]
            self.anomalies_total += len(events)
            if governor is not None:
                try:
                    governor.note_advisory()
                except Exception:
                    pass
        # publish: the dict is complete before the reference swap, and
        # never mutated after — a concurrent scrape sees old or new,
        # never a torn mix
        self._last_window = window
        self._recent_windows.append(window)
        self.windows_total += 1
        self._window_id += 1
        self._steps_in_window = 0
        self._accum = {}

    # -- calibration-drift sentinel ---------------------------------------

    def _check_calibration(self, rollups) -> None:
        """Live achieved wire GB/s per wire class (rollup wire bytes /
        wire time; the handoff tier is the DCN class, everything else
        ICI) vs the persisted ``LinkCalibration`` rate —
        ``tools.calibrate.wire_gbps``, the SAME number the SOL /
        ``pct_sol`` attribution divides by.  Sustained divergence
        (> ``LINKCAL_DRIFT_PCT`` for ``LINKCAL_SUSTAIN`` consecutive
        windows) marks the class stale so attributions can't silently
        rot when topology changes.  A class with no wire signal this
        window (no bytes or no wire time) gets no verdict — the streak
        holds."""
        sums: dict[str, list[float]] = {}
        for r in rollups:
            cls = "dcn" if r.tier == "handoff" else "ici"
            cur = sums.setdefault(cls, [0.0, 0.0])
            cur[0] += float(r.wire_bytes)
            cur[1] += float(r.wire_us)
        for cls, (nbytes, us) in sums.items():
            if nbytes <= 0 or us <= 0:
                continue
            try:
                from ..tools import calibrate

                expected = float(calibrate.wire_gbps(cls))
            except Exception:
                continue
            if expected <= 0:
                continue
            achieved = nbytes / (us * 1e3)     # bytes/us -> GB/s
            divergence = abs(achieved - expected) / expected
            if divergence > LINKCAL_DRIFT_PCT:
                n = self._linkcal_streak.get(cls, 0) + 1
                self._linkcal_streak[cls] = n
                if n >= LINKCAL_SUSTAIN:
                    self._linkcal_stale[cls] = {
                        "wire_class": cls,
                        "achieved_gbps": round(achieved, 3),
                        "calibrated_gbps": round(expected, 3),
                        "divergence_pct": round(100 * divergence, 1),
                        "windows": n,
                    }
            else:
                self._linkcal_streak[cls] = 0
                self._linkcal_stale.pop(cls, None)

    def calibration_drift(self) -> dict[str, dict]:
        """Per-class stale-calibration verdicts (empty = healthy)."""
        with self._lock:
            return dict(self._linkcal_stale)

    # -- persistence -------------------------------------------------------

    def _persist(self, window: dict) -> None:
        if not self.out_dir:
            return
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            if self._segment_path is None:
                self._segment_path = os.path.join(
                    self.out_dir, f"profile_{self._segment_idx:04d}.jsonl")
            line = json.dumps(window, separators=(",", ":"),
                              default=str)
            with open(self._segment_path, "a") as f:
                f.write(line + "\n")
            if os.path.getsize(self._segment_path) >= SEGMENT_MAX_BYTES:
                self._segment_idx += 1
                self._segment_path = None
                self._prune_segments()
        except OSError:
            # a full/unwritable disk must not take the serve loop down;
            # the in-memory windows and /metrics keep working
            pass

    def _prune_segments(self) -> None:
        import glob as _glob
        import re as _re

        rx = _re.compile(r"profile_(\d+)\.jsonl$")
        segs = []
        for p in _glob.glob(os.path.join(self.out_dir,
                                         "profile_*.jsonl")):
            m = rx.search(p)
            if m:
                segs.append((int(m.group(1)), p))
        segs.sort()
        for _, p in segs[:-MAX_SEGMENTS]:
            try:
                os.remove(p)
            except OSError:
                pass

    # -- read side ---------------------------------------------------------

    def last_window(self) -> dict | None:
        """The most recently rotated window (immutable once
        published)."""
        return self._last_window

    def lifetime_rollups(self) -> dict[tuple, Rollup]:
        """Cumulative per-key rollups since construction (the coverage
        view; copy under the lock)."""
        with self._lock:
            return dict(self._lifetime)

    def snapshot(self) -> dict:
        """The ``/debug/profile`` payload."""
        from . import anomaly

        with self._lock:
            return {
                "enabled": enabled(),
                "window_steps": self.window_steps,
                "windows_total": self.windows_total,
                "anomalies_total": self.anomalies_total,
                "open_window": {
                    "id": self._window_id,
                    "steps": self._steps_in_window,
                    "rollup_keys": len(self._accum),
                },
                "last_window": self._last_window,
                "exposed_ms": {
                    "p50": self.exposed_ms_sketch.quantile(0.5),
                    "p99": self.exposed_ms_sketch.quantile(0.99),
                },
                "overlap_hidden_pct": {
                    "p50": self.overlap_sketch.quantile(0.5),
                    "p99": self.overlap_sketch.quantile(0.99),
                },
                "anomalies": [e.to_dict() for e in anomaly.recent()],
                "segments": {
                    "dir": self.out_dir,
                    "current": self._segment_path,
                    "index": self._segment_idx,
                },
            }


# ---------------------------------------------------------------------------
# module singleton + the hook call sites use


def profiler() -> ContinuousProfiler | None:
    """The process profiler, if one has been created (armed step seen
    or :func:`install` called)."""
    return _PROFILER


def install(prof: ContinuousProfiler | None) -> ContinuousProfiler | None:
    """Install (or clear, with None) the process profiler — the harness
    entry for custom window sizes.  Returns the previous one."""
    global _PROFILER
    with _LOCK:
        prev, _PROFILER = _PROFILER, prof
    return prev


def _get_profiler() -> ContinuousProfiler:
    global _PROFILER
    if _PROFILER is None:
        with _LOCK:
            if _PROFILER is None:
                _PROFILER = ContinuousProfiler()
    return _PROFILER


def on_step(tier: str, step: int, governor=None) -> None:
    """The scheduler step-boundary hook (``serve.Scheduler._step_impl``
    and the router's handoff pump).  One cached-bool check when
    ``TDT_PROFILE`` is unset — byte-identical behavior."""
    if not _ENABLED:
        return
    if _suppressed():
        return
    _get_profiler().on_step(tier, step, governor=governor)


def reset() -> None:
    """Drop the process profiler (tests / lint harness hygiene)."""
    install(None)


def calibration_fragment() -> dict | None:
    """What ``resilience.health_snapshot`` attaches under ``linkcal``
    when a wire class's live achieved rate has diverged from the
    persisted calibration for ``LINKCAL_SUSTAIN`` consecutive windows:
    a WARNING naming the stale wire class — never a status flip
    (drift must not 503 a serving replica; the PR-15 rule), and None
    when healthy so an unarmed snapshot is byte-identical."""
    prof = _PROFILER
    if prof is None:
        return None
    stale = prof.calibration_drift()
    if not stale:
        return None
    return {
        "status": "warn",
        "stale_wire_classes": sorted(stale),
        "detail": stale,
        "hint": "re-run tools/calibrate.py — SOL/pct_sol attributions "
                "assume the persisted rates",
    }


# ---------------------------------------------------------------------------
# exposition


def to_prometheus() -> str:
    """Profiler gauges for ``/metrics`` (appended by
    ``obs.server.metrics_text``): last-window per-key rollups plus the
    window counters.  Empty when no window has rotated."""
    prof = _PROFILER
    if prof is None:
        return ""
    window = prof.last_window()
    if window is None:
        return ""
    lines = [
        "# TYPE tdt_profile_windows_total counter",
        f"tdt_profile_windows_total {prof.windows_total}",
        "# TYPE tdt_profile_anomalies_total counter",
        f"tdt_profile_anomalies_total {prof.anomalies_total}",
    ]
    for name in ("overlap_hidden_pct", "exposed_us", "pct_sol",
                 "skew_us", "episodes"):
        lines.append(f"# TYPE tdt_profile_{name} gauge")
        for r in window["rollups"]:
            labels = (f'family="{r["family"]}",'
                      f'topology="{r["topology"]}",tier="{r["tier"]}"')
            lines.append(f"tdt_profile_{name}{{{labels}}} {r[name]}")
    return "\n".join(lines) + "\n"


def format_snapshot(snap: dict) -> str:
    """Human-readable rendering of a :meth:`ContinuousProfiler.snapshot`
    payload (``scripts/obs_report.py --live``)."""
    lines = [
        f"continuous profiler: enabled={snap.get('enabled')} "
        f"windows={snap.get('windows_total', 0)} "
        f"window_steps={snap.get('window_steps')} "
        f"anomalies={snap.get('anomalies_total', 0)}",
    ]
    window = snap.get("last_window")
    if not window:
        lines.append("(no rotated window yet — is TDT_PROFILE armed and "
                     "the serve loop stepping?)")
        return "\n".join(lines) + "\n"
    lines.append(f"last window #{window['window']} "
                 f"(ends step {window['step_end']}, "
                 f"{window['steps']} steps):")
    header = ("family", "topology", "tier", "episodes", "hidden%",
              "exposed_ms", "pct_sol", "skew_us")
    rows = [header]
    for r in sorted(window.get("rollups", []),
                    key=lambda r: (r["tier"], r["family"])):
        rows.append((r["family"], r["topology"], r["tier"],
                     str(r["episodes"]),
                     f"{r['overlap_hidden_pct']:.1f}",
                     f"{r['exposed_us'] / 1e3:.3f}",
                     f"{100 * r['pct_sol']:.1f}",
                     f"{r['skew_us']:.1f}"))
    widths = [max(len(row[i]) for row in rows)
              for i in range(len(header))]
    for i, row in enumerate(rows):
        lines.append("  ".join(c.ljust(w)
                               for c, w in zip(row, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    tot = window.get("totals", {})
    lines.append(
        f"totals: exposed={tot.get('exposed_ms', 0):.3f}ms "
        f"hidden={tot.get('overlap_hidden_pct', 0):.1f}% "
        f"pct_sol={100 * tot.get('pct_sol', 0):.1f}% "
        f"dominant_stall={tot.get('dominant_stall')}")
    for a in snap.get("anomalies", []):
        lines.append(f"ANOMALY {a.get('summary', a)}")
    return "\n".join(lines) + "\n"
