"""Overlap-efficiency report from (merged) Chrome-trace span files.

The paper's design goal is tile-granular compute–communication overlap;
this module turns recorded spans into the number that goal is measured
by.  For every ``cat: "step"`` span (one serving iteration), the comm
intervals (``cat: "comm"``) inside it are intersected against the
compute intervals (``cat: "compute"``):

    comm_total   = |union(comm)|
    comm_exposed = |union(comm) - union(compute)|   (comm not hidden
                                                     under any compute)
    overlap      = 1 - comm_exposed / comm_total    (1.0 = fully hidden)

A step with no comm spans reports ``overlap = None`` (nothing to hide —
excluded from aggregates rather than counted as a free 1.0).  Steps are
grouped per pid (per process/rank: ``tools.trace_merge`` offsets each
rank's pids by 1e6, so rank lanes never mix), which also makes the
arithmetic immune to cross-host clock skew.

Consumed by ``scripts/obs_report.py``; spans come from ``obs.tracing``
exports, one file per process, merged with ``tools.trace_merge``.
"""

from __future__ import annotations

import gzip
import json

STEP_CAT = "step"
COMM_CAT = "comm"
COMPUTE_CAT = "compute"


def load_trace(path: str) -> list[dict]:
    """Events of a Chrome-trace JSON file (``.gz`` transparent)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        trace = json.load(f)
    if isinstance(trace, list):  # bare event-array form is legal chrome trace
        return trace
    return trace.get("traceEvents", [])


def _union(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Merge overlapping/touching intervals; result sorted and disjoint."""
    out: list[list[float]] = []
    for b, e in sorted(i for i in intervals if i[1] > i[0]):
        if out and b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]


def _total(intervals: list[tuple[float, float]]) -> float:
    return sum(e - b for b, e in intervals)


def _subtract(a: list[tuple[float, float]],
              b: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """``union(a) - union(b)`` as disjoint intervals."""
    a = _union(a)
    b = _union(b)
    out: list[tuple[float, float]] = []
    j = 0
    for b0, e0 in a:
        cur = b0
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < e0:
            bb, be = b[k]
            if bb > cur:
                out.append((cur, bb))
            cur = max(cur, be)
            if cur >= e0:
                break
            k += 1
        if cur < e0:
            out.append((cur, e0))
    return out


def _clip(intervals, lo: float, hi: float) -> list[tuple[float, float]]:
    return [(max(b, lo), min(e, hi)) for b, e in intervals
            if min(e, hi) > max(b, lo)]


def overlap_report(events: list[dict]) -> list[dict]:
    """Per-step overlap rows from complete (``ph: X``) span events.

    Returns one dict per step span, ordered by (pid, start time):
    ``pid``, ``rank`` (pid // 1e6 — the trace_merge offset), ``step``
    (name), ``idx`` (per-pid ordinal), ``t_ms`` (step duration),
    ``compute_ms``, ``comm_ms``, ``exposed_ms``, ``overlap``.
    """
    by_pid: dict[int, dict[str, list]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        cat = ev.get("cat")
        if cat not in (STEP_CAT, COMM_CAT, COMPUTE_CAT):
            continue
        pid = int(ev.get("pid", 0))
        lane = by_pid.setdefault(pid, {STEP_CAT: [], COMM_CAT: [],
                                       COMPUTE_CAT: []})
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        lane[cat].append((ts, ts + dur, ev.get("name", "")))

    rows: list[dict] = []
    for pid in sorted(by_pid):
        lane = by_pid[pid]
        comm = [(b, e) for b, e, _ in lane[COMM_CAT]]
        compute = [(b, e) for b, e, _ in lane[COMPUTE_CAT]]
        for idx, (b, e, name) in enumerate(sorted(lane[STEP_CAT])):
            c_in = _clip(comm, b, e)
            x_in = _clip(compute, b, e)
            comm_u = _union(c_in)
            comm_ms = _total(comm_u) / 1e3
            exposed_ms = _total(_subtract(comm_u, x_in)) / 1e3
            overlap = (1.0 - exposed_ms / comm_ms) if comm_ms > 0 else None
            rows.append({
                "pid": pid, "rank": pid // 1_000_000, "step": name,
                "idx": idx, "t_ms": (e - b) / 1e3,
                "compute_ms": _total(_union(x_in)) / 1e3,
                "comm_ms": comm_ms, "exposed_ms": exposed_ms,
                "overlap": overlap,
            })
    return rows


def aggregate(rows: list[dict]) -> dict:
    """Whole-trace summary: mean/min overlap over steps that had comm,
    plus total comm-exposed milliseconds (the time overlap failed to
    hide — the quantity every perf PR should shrink)."""
    with_comm = [r for r in rows if r["overlap"] is not None]
    if not with_comm:
        return {"steps": len(rows), "steps_with_comm": 0,
                "mean_overlap": None, "min_overlap": None,
                "exposed_ms_total": 0.0}
    return {
        "steps": len(rows),
        "steps_with_comm": len(with_comm),
        "mean_overlap": sum(r["overlap"] for r in with_comm) / len(with_comm),
        "min_overlap": min(r["overlap"] for r in with_comm),
        "exposed_ms_total": sum(r["exposed_ms"] for r in with_comm),
    }


def format_report(rows: list[dict]) -> str:
    """The per-step overlap-efficiency table + aggregate footer."""
    header = ("rank", "step", "idx", "t_ms", "compute_ms", "comm_ms",
              "exposed_ms", "overlap")
    table = [header]
    for r in rows:
        table.append((
            str(r["rank"]), r["step"], str(r["idx"]), f"{r['t_ms']:.3f}",
            f"{r['compute_ms']:.3f}", f"{r['comm_ms']:.3f}",
            f"{r['exposed_ms']:.3f}",
            "-" if r["overlap"] is None else f"{r['overlap']:.3f}",
        ))
    widths = [max(len(row[i]) for row in table) for i in range(len(header))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(c.rjust(w) if j != 1 else c.ljust(w)
                               for j, (c, w) in enumerate(zip(row, widths))))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    agg = aggregate(rows)
    lines.append("")
    if agg["steps_with_comm"]:
        lines.append(
            f"steps: {agg['steps']} ({agg['steps_with_comm']} with comm)  "
            f"mean overlap: {agg['mean_overlap']:.3f}  "
            f"min overlap: {agg['min_overlap']:.3f}  "
            f"comm exposed total: {agg['exposed_ms_total']:.3f} ms"
        )
    else:
        lines.append(f"steps: {agg['steps']} (none recorded comm spans)")
    return "\n".join(lines) + "\n"


def selftest() -> str:
    """Canned two-rank span set with known overlap ratios; raises on any
    mismatch, returns the formatted table (``obs_report.py --selftest``).

    Rank 0 (pid 0): step A's comm [10, 20] fully inside compute [5, 25]
    -> overlap 1.0; step B's comm [110, 130] half-covered by compute
    [120, 140] -> overlap 0.5.  Rank 1 (pid 1e6): comm [15, 25] with no
    compute -> overlap 0.0; a comm-less step -> overlap None.
    """
    us = 1000.0  # all canned times in ms for readability

    def ev(name, cat, pid, b_ms, e_ms):
        return {"name": name, "cat": cat, "ph": "X", "pid": pid, "tid": 0,
                "ts": b_ms * us, "dur": (e_ms - b_ms) * us}

    events = [
        ev("decode_step", "step", 0, 0, 30),
        ev("mlp", "compute", 0, 5, 25),
        ev("all_gather", "comm", 0, 10, 20),
        ev("decode_step", "step", 0, 100, 150),
        ev("mlp", "compute", 0, 120, 140),
        ev("all_gather", "comm", 0, 110, 130),
        ev("decode_step", "step", 1_000_000, 0, 40),
        ev("all_reduce", "comm", 1_000_000, 15, 25),
        ev("decode_step", "step", 1_000_000, 100, 120),
    ]
    rows = overlap_report(events)
    want = [1.0, 0.5, 0.0, None]
    got = [r["overlap"] for r in rows]
    for w, g in zip(want, got):
        ok = (g is None) if w is None else (g is not None
                                            and abs(g - w) < 1e-9)
        if not ok:
            raise AssertionError(f"selftest overlap mismatch: want {want}, "
                                 f"got {got}")
    agg = aggregate(rows)
    if abs(agg["mean_overlap"] - 0.5) > 1e-9 or agg["steps_with_comm"] != 3:
        raise AssertionError(f"selftest aggregate mismatch: {agg}")
    return format_report(rows)
