"""Typed, bounded control-decision ledger for the fleet tier
(``TDT_FLEET_OBS=1``).

The ``serve.fleet.FleetRouter`` actuates autonomously — it routes
admissions on live gauges, sheds, fails requests over, walks replicas
through quarantine, and converts replica roles on the SLO attributor's
say-so — but until ISSUE 19 those actuations left no record of *which*
telemetry reads drove them.  This module is the controller's flight
recorder: every actuation site emits a :class:`DecisionRecord` carrying
its inputs verbatim (the gauge values read, breaker states, the
dominant_phase and sustained-streak count behind a rebalance, the p99
exemplar trace id where one drove the decision) plus the affected
request/replica ids.

Records are retained two ways, exactly like the PR-15 profiler's
windows: a bounded in-memory ring (``TDT_DECISION_RING``, default 512)
served by ``/debug/fleet`` and the fleet anomaly events, and an
optional size-rotated JSONL time-series (``TDT_DECISION_DIR``:
``decisions_NNNN.jsonl`` segments, oldest deleted —
``obs.history.load_decision_records`` parses them back).

The kind axis is TYPED: :data:`DECISION_KINDS` is the golden map from
decision kind to the ``FleetRouter`` method(s) that emit it, and
``analysis.completeness.check_decision_coverage`` diffs it both
directions against the live actuation sites — an actuation added
without a ledger emit (or a golden row whose site vanished) fails
``tdt_lint --completeness`` with the diff as the message.

The TDT_OBS discipline holds: with ``TDT_FLEET_OBS`` unset every hook
is one cached-bool check and the fleet replay is byte-identical
(pinned by ``tests/test_fleet_obs.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque

DEFAULT_RING = 512
# on-disk time-series bounds, shared with the profiler's discipline:
# segments rotate at this size, oldest beyond the cap are deleted
SEGMENT_MAX_BYTES = 256 * 1024
MAX_SEGMENTS = 8

# The golden kind axis: decision kind -> the FleetRouter method(s) that
# record it (via ``FleetRouter._decide``).  completeness.
# check_decision_coverage diffs this against the live source both
# directions, so the table below IS the contract — extending the
# controller means extending this map in the same PR.
DECISION_KINDS: dict[str, tuple[str, ...]] = {
    # admission plane
    "affinity_hit": ("submit",),            # session routed to its home
    "affinity_redirect": ("submit",),       # home unavailable, rerouted
    "route": ("submit",),                   # least-loaded admission pick
    "shed": ("submit",),                    # no admitting replica
    "colocate": ("_colocate",),             # saturation shed-back rule
    # failure plane
    "replica_lost": ("lose_replica",),
    "failover": ("_failover",),
    "failover_shed": ("_failover",),        # ladder exhausted
    "reprefill": ("_reprefill",),           # handoff fallback re-prefill
    # quarantine lifecycle (open -> drain -> probe -> close)
    "quarantine_drain": ("_watch_failures", "_quarantine_tick"),
    "quarantine_evict": ("_quarantine_tick",),
    "readmit_probe": ("_probe_tick",),
    "readmit": ("readmit",),
    # rebalance plane
    "rebalance_streak": ("_rebalance_tick",),
    "recruit": ("_rebalance_tick",),
    "convert": ("_convert",),
}


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_FLEET_OBS")


# Cached so a disabled actuation site pays one global load + one bool
# check (the TDT_OBS discipline); re-read the env via enable(None).
_ENABLED = _env_enabled()

_LOCK = threading.Lock()
_LEDGER: "DecisionLedger | None" = None

_pkg_cache: list = []


def _suppressed() -> bool:
    """Honor ``obs.suppress()``: quarantine probes and warmup traffic
    drive the same actuation sites but must not pollute the ledger."""
    if not _pkg_cache:
        import sys

        _pkg_cache.append(sys.modules[__package__])
    return _pkg_cache[0]._suppressed()


def enabled() -> bool:
    """Whether the ledger records (``TDT_FLEET_OBS=1`` or
    :func:`enable`, and not inside an ``obs.suppress()`` block)."""
    return _ENABLED and not _suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn the ledger on/off; ``None`` re-reads ``TDT_FLEET_OBS``."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


def ring_cap() -> int:
    """In-memory retention (``TDT_DECISION_RING``, default 512)."""
    try:
        return max(1, int(os.environ.get("TDT_DECISION_RING", "")
                          or DEFAULT_RING))
    except ValueError:
        return DEFAULT_RING


def decision_dir() -> str | None:
    """Where the JSONL segments land (``TDT_DECISION_DIR``); None
    disables persistence (ring only)."""
    return os.environ.get("TDT_DECISION_DIR", "").strip() or None


@dataclasses.dataclass(frozen=True)
class DecisionRecord:
    """One controller actuation, inputs verbatim.

    ``inputs`` carries exactly the values the decision read — gauge
    reads, breaker states, the dominant_phase / streak behind a
    rebalance, a ``p99_exemplar`` trace id where one drove the call —
    so a regressed fleet window can be explained from its ledger tail
    alone, without re-deriving controller state."""

    seq: int
    step: int
    t_us: float                      # wall-anchored us (Chrome lanes)
    kind: str
    replica: str | None = None
    request_id: int | None = None
    session: str | None = None
    inputs: dict = dataclasses.field(default_factory=dict)
    note: str | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        who = self.replica or "-"
        req = f" req={self.request_id}" if self.request_id is not None \
            else ""
        return (f"step {self.step}: {self.kind} @{who}{req}"
                + (f" ({self.note})" if self.note else ""))


def from_dict(d: dict) -> DecisionRecord:
    """Rehydrate a persisted JSONL line (``obs.history.
    load_decision_records`` hands dicts here)."""
    return DecisionRecord(
        seq=int(d.get("seq", 0)),
        step=int(d.get("step", 0)),
        t_us=float(d.get("t_us", 0.0)),
        kind=str(d["kind"]),
        replica=d.get("replica"),
        request_id=d.get("request_id"),
        session=d.get("session"),
        inputs=dict(d.get("inputs") or {}),
        note=d.get("note"),
    )


class DecisionLedger:
    """The bounded decision store (one per process under the module
    singleton; harnesses may install their own via :func:`install`).
    All mutation happens under one lock; reads copy, so concurrent
    ``/debug/fleet`` scrapes never see a torn tail."""

    def __init__(self, *, cap: int | None = None,
                 out_dir: str | None = None):
        self.cap = int(cap) if cap else ring_cap()
        self.out_dir = out_dir if out_dir is not None else decision_dir()
        self._lock = threading.RLock()
        self._ring: deque[DecisionRecord] = deque(maxlen=self.cap)
        self.total = 0
        self._by_kind: dict[str, int] = {}
        self._segment_idx = 0
        self._segment_path: str | None = None

    # -- write side --------------------------------------------------------

    def record(self, kind: str, *, step: int, replica: str | None = None,
               request_id: int | None = None, session: str | None = None,
               inputs: dict | None = None,
               note: str | None = None) -> DecisionRecord:
        if kind not in DECISION_KINDS:
            raise ValueError(
                f"unknown decision kind {kind!r} — the ledger is typed; "
                f"add the kind to obs.decisions.DECISION_KINDS (and its "
                f"actuation site to the golden) first")
        with self._lock:
            rec = DecisionRecord(
                seq=self.total, step=int(step),
                t_us=time.time_ns() / 1e3, kind=kind, replica=replica,
                request_id=request_id, session=session,
                inputs=dict(inputs or {}), note=note)
            self._ring.append(rec)
            self.total += 1
            self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
            self._persist(rec)
        return rec

    # -- read side ---------------------------------------------------------

    def tail(self, n: int | None = None) -> list[DecisionRecord]:
        with self._lock:
            recs = list(self._ring)
        return recs if n is None else recs[-max(0, int(n)):]

    def query(self, *, replica: str | None = None,
              kind: str | None = None,
              step_range: tuple[int, int] | None = None,
              ) -> list[DecisionRecord]:
        """Retained records filtered by replica / kind / step window
        (``step_range`` is inclusive of both ends)."""
        out = []
        for rec in self.tail():
            if replica is not None and rec.replica != replica:
                continue
            if kind is not None and rec.kind != kind:
                continue
            if step_range is not None and not (
                    step_range[0] <= rec.step <= step_range[1]):
                continue
            out.append(rec)
        return out

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._by_kind)

    def snapshot(self, n: int = 64) -> dict:
        """The ``/debug/fleet`` ledger block."""
        with self._lock:
            return {
                "cap": self.cap,
                "total": self.total,
                "counts": dict(self._by_kind),
                "tail": [r.to_dict() for r in list(self._ring)[-n:]],
                "segments": {
                    "dir": self.out_dir,
                    "current": self._segment_path,
                    "index": self._segment_idx,
                },
            }

    # -- persistence -------------------------------------------------------

    def _persist(self, rec: DecisionRecord) -> None:
        if not self.out_dir:
            return
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            if self._segment_path is None:
                self._segment_path = os.path.join(
                    self.out_dir,
                    f"decisions_{self._segment_idx:04d}.jsonl")
            line = json.dumps(rec.to_dict(), separators=(",", ":"),
                              default=str)
            with open(self._segment_path, "a") as f:
                f.write(line + "\n")
            if os.path.getsize(self._segment_path) >= SEGMENT_MAX_BYTES:
                self._segment_idx += 1
                self._segment_path = None
                self._prune_segments()
        except OSError:
            # a full/unwritable disk must not take the control plane
            # down; the ring and /debug/fleet keep working
            pass

    def _prune_segments(self) -> None:
        import glob as _glob
        import re as _re

        rx = _re.compile(r"decisions_(\d+)\.jsonl$")
        segs = []
        for p in _glob.glob(os.path.join(self.out_dir,
                                         "decisions_*.jsonl")):
            m = rx.search(p)
            if m:
                segs.append((int(m.group(1)), p))
        segs.sort()
        for _, p in segs[:-MAX_SEGMENTS]:
            try:
                os.remove(p)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# module singleton + the hook call sites use


def ledger() -> DecisionLedger | None:
    """The process ledger, if one has been created (armed actuation
    seen or :func:`install` called)."""
    return _LEDGER


def install(led: DecisionLedger | None) -> DecisionLedger | None:
    """Install (or clear, with None) the process ledger — the harness
    entry for custom caps/dirs.  Returns the previous one."""
    global _LEDGER
    with _LOCK:
        prev, _LEDGER = _LEDGER, led
    return prev


def _get_ledger() -> DecisionLedger:
    global _LEDGER
    if _LEDGER is None:
        with _LOCK:
            if _LEDGER is None:
                _LEDGER = DecisionLedger()
    return _LEDGER


def record(kind: str, **kw) -> DecisionRecord | None:
    """The actuation-site hook (``FleetRouter._decide``).  One
    cached-bool check when ``TDT_FLEET_OBS`` is unset — byte-identical
    fleet behavior; None inside ``obs.suppress()`` (probe traffic)."""
    if not _ENABLED:
        return None
    if _suppressed():
        return None
    return _get_ledger().record(kind, **kw)


def query(**kw) -> list[DecisionRecord]:
    """Query the retained ring (empty when no ledger exists yet)."""
    led = _LEDGER
    return [] if led is None else led.query(**kw)


def reset() -> None:
    """Drop the process ledger (tests / lint harness hygiene)."""
    install(None)


# ---------------------------------------------------------------------------
# exposition


def tail_dump(n: int = 64) -> dict:
    """The ledger block of the ``/debug/fleet`` payload."""
    led = _LEDGER
    if led is None:
        return {"enabled": enabled(), "total": 0, "counts": {},
                "tail": []}
    out = led.snapshot(n=n)
    out["enabled"] = enabled()
    return out


def to_prometheus() -> str:
    """Decision counters for ``/metrics`` (appended by
    ``obs.server.metrics_text``).  Empty when nothing recorded."""
    led = _LEDGER
    if led is None or led.total == 0:
        return ""
    lines = [
        "# TYPE tdt_fleet_decisions_total counter",
        f"tdt_fleet_decisions_total {led.total}",
        "# TYPE tdt_fleet_decisions counter",
    ]
    for kind, n in sorted(led.counts().items()):
        lines.append(f'tdt_fleet_decisions{{kind="{kind}"}} {n}')
    return "\n".join(lines) + "\n"


def format_tail(records, limit: int = 24) -> str:
    """Human-readable ledger tail (``obs_report.py --fleet``)."""
    recs = list(records)[-limit:]
    if not recs:
        return "(decision ledger empty)\n"
    lines = []
    for r in recs:
        d = r.to_dict() if hasattr(r, "to_dict") else dict(r)
        who = d.get("replica") or "-"
        req = d.get("request_id")
        parts = [f"  #{d.get('seq', '?')} step={d.get('step')} "
                 f"{d.get('kind'):<18} replica={who}"]
        if req is not None:
            parts.append(f"req={req}")
        if d.get("note"):
            parts.append(f"note={d['note']}")
        ins = d.get("inputs") or {}
        if ins:
            parts.append("inputs=" + json.dumps(ins, sort_keys=True,
                                                default=str))
        lines.append(" ".join(parts))
    return "\n".join(lines) + "\n"
