"""Cross-rank collective flight recorder.

The reference merges per-rank profiler traces into one timeline to show
*where* an overlapped kernel spends its time; this module is the
always-armable equivalent for the PROTOCOL layer: every distributed
primitive (``lang/primitives.py``: wait / notify / remote_copy /
local_copy / wait_recv / wait_send / barrier) reports through the same
thread-local interception points the analysis recorder and the fault
injector already use, and the flight recorder captures the stream —
semaphore identity, destination chunk, peer, credit size, monotonic
timestamp — into a bounded ring buffer.

Two capture modes:

- **global ring** (``TDT_FLIGHT=1`` or :func:`enable`): every event on
  any thread lands in one process-wide ring with last-N-steps retention
  (``TDT_FLIGHT_STEPS``, default 8; the engine marks step boundaries).
  When a collective times out or a serve step fails, the recent history
  is attached to the diagnosis (``resilience.watchdog`` /
  ``models.engine._mark_failed``) — "what was the protocol doing just
  before it died", not just "it died".  Off (the default) a primitive
  pays one thread-local read; the engine's per-step mark pays one cached
  bool.
- **per-rank capture** (:func:`capture` / :func:`record_case`): the
  deterministic harness — run every rank of an ``analysis.registry``
  kernel case under record mode with a capture installed, yielding one
  event stream per rank.  ``obs.timeline`` reconstructs those streams
  into a cross-rank timeline with per-wait attribution; this is what
  ``scripts/obs_report.py --timeline`` and ``scripts/tdt_lint.py
  --timeline`` run on.

Event identity is symbolic where available (record mode: ``FakeSem``
labels, ``FakeRef`` region labels) and best-effort live (trace-time
objects have no stable names; the op/step context still does).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from collections import deque

# ring capacity: ~120 B/event slotted; 100k events ≈ 12 MB worst case
MAX_EVENTS = 100_000

_tls = threading.local()
_lock = threading.Lock()
_ring: deque = deque(maxlen=MAX_EVENTS)
_state = {"step": 0}


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_FLIGHT")


_ENABLED = _env_enabled()

_pkg_cache: list = []


def _suppressed() -> bool:
    """Measurement-only traffic (autotune sweeps, serve warmup) runs
    under ``obs.suppress()``; the flight ring honors the same marker —
    a timeout dump must show the serving protocol's history, not
    hundreds of sweep markers (see ``obs.suppress``)."""
    if not _pkg_cache:
        import sys

        _pkg_cache.append(sys.modules[__package__])
    return _pkg_cache[0]._suppressed()


def enabled() -> bool:
    """Whether the global ring records (``TDT_FLIGHT=1`` or
    :func:`enable`, and not inside an ``obs.suppress()`` block on this
    thread)."""
    return _ENABLED and not _suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn the global ring on/off; ``None`` re-reads ``TDT_FLIGHT``."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


def keep_steps() -> int:
    try:
        return max(1, int(os.environ.get("TDT_FLIGHT_STEPS", "") or 8))
    except ValueError:
        return 8


@dataclasses.dataclass
class FlightEvent:
    """One captured primitive event.  ``elems`` is the credit size in the
    semaphore's own unit (counts for regular, elements for DMA);
    ``flops``/``bytes`` are filled for compute events (from
    ``obs.costs`` arithmetic over the recorded regions)."""

    __slots__ = ("kind", "t_us", "rank", "sem", "sem2", "chunk", "peer",
                 "elems", "flops", "bytes", "op", "step")

    kind: str                 # wait|notify|remote_copy|local_copy|wait_recv|
    #                           wait_send|barrier|compute|collective|step
    t_us: float               # monotonic capture time (us)
    rank: int                 # recording rank; -1 = live / unknown
    sem: str | None           # primary semaphore (recv side for copies)
    sem2: str | None          # send-completion semaphore of a remote_copy
    chunk: str | None         # destination region label, if known
    peer: int | None          # device id on the other end, if known
    elems: int
    flops: int
    bytes: int
    op: str | None            # enclosing collective / compute kind
    step: int                 # serving-step ordinal at capture

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d: dict) -> "FlightEvent":
        return cls(**{k: d.get(k, 0 if k in ("elems", "flops", "bytes",
                                             "step") else None)
                      for k in cls.__slots__})

    def describe(self) -> str:
        bits = [f"rank {self.rank}" if self.rank >= 0 else "live",
                self.kind]
        if self.op:
            bits.append(f"op={self.op}")
        if self.sem:
            bits.append(f"sem={self.sem}")
        if self.elems:
            bits.append(f"n={self.elems}")
        if self.chunk:
            bits.append(f"chunk={self.chunk}")
        if self.peer is not None:
            bits.append(f"peer={self.peer}")
        if self.bytes:
            bits.append(f"bytes={self.bytes}")
        return f"[step {self.step} t={self.t_us:.1f}us] " + " ".join(bits)


def _now_us() -> float:
    return time.perf_counter_ns() / 1e3


def _sem_label(sem) -> str | None:
    """Symbolic identity when the arg is an analysis FakeSem; a stable
    best-effort label otherwise (live trace-time objects are unnamed)."""
    label = getattr(sem, "label", None)
    if callable(label):
        try:
            return label()
        except Exception:
            pass
    if sem is None:
        return None
    return type(sem).__name__


def _region(ref):
    r = getattr(ref, "region", None)
    if callable(r):
        try:
            return r()
        except Exception:
            return None
    return None


def _region_label(ref) -> str | None:
    reg = _region(ref)
    return reg.label() if reg is not None else None


def _region_elems(ref) -> int:
    reg = _region(ref)
    return reg.elements() if reg is not None else 0


def _as_peer(device_id) -> int | None:
    try:
        return int(device_id)
    except Exception:
        return None


class FlightSink:
    """Hook target ``lang.primitives`` talks to.  The global sink writes
    the process ring; :class:`FlightCapture` writes its own stream."""

    rank = -1

    def _emit(self, ev: FlightEvent) -> None:
        _ring.append(ev)

    def _event(self, kind: str, *, sem=None, sem2=None, chunk=None,
               peer=None, elems: int = 0, flops: int = 0, nbytes: int = 0,
               op: str | None = None) -> None:
        self._emit(FlightEvent(kind, _now_us(), self.rank, sem, sem2, chunk,
                               peer, int(elems), int(flops), int(nbytes), op,
                               _state["step"]))

    # -- primitive hooks (lang/primitives.py call sites) --------------------

    def on_wait(self, sem, value) -> None:
        try:
            v = int(value)
        except Exception:
            v = 0
        self._event("wait", sem=_sem_label(sem), elems=v)

    def on_notify(self, sem, device_id, inc) -> None:
        try:
            v = int(inc)
        except Exception:
            v = 0
        self._event("notify", sem=_sem_label(sem), peer=_as_peer(device_id),
                    elems=v)

    def on_remote_copy(self, src, dst, send_sem, recv_sem, device_id) -> None:
        self._event("remote_copy", sem=_sem_label(recv_sem),
                    sem2=_sem_label(send_sem), chunk=_region_label(dst),
                    peer=_as_peer(device_id), elems=_region_elems(dst))

    def on_local_copy(self, src, dst, sem) -> None:
        self._event("local_copy", sem=_sem_label(sem),
                    chunk=_region_label(dst), elems=_region_elems(dst))

    def on_wait_recv(self, dst_ref, sem) -> None:
        self._event("wait_recv", sem=_sem_label(sem),
                    chunk=_region_label(dst_ref),
                    elems=_region_elems(dst_ref))

    def on_wait_send(self, src_ref, sem) -> None:
        self._event("wait_send", sem=_sem_label(sem),
                    chunk=_region_label(src_ref),
                    elems=_region_elems(src_ref))

    def on_barrier(self, kind: str, team, sem) -> None:
        self._event("barrier", sem=_sem_label(sem), op=kind,
                    elems=int(team.size))

    def on_compute(self, kind: str, refs) -> None:
        """From the ``ops.blocks`` pipeline stubs (record mode): derive
        flop/byte counts from the recorded regions via the same
        arithmetic ``obs.costs`` uses for the builders."""
        reads, write = refs[:-1], refs[-1]
        flops = nbytes = 0
        regions = [_region(r) for r in reads if _region(r) is not None]
        wreg = _region(write)
        if kind == "matmul" and len(regions) >= 2:
            def dims(reg):
                return [hi - lo for lo, hi in reg.bounds]
            a, b = dims(regions[0]), dims(regions[1])
            if len(a) >= 2 and len(b) >= 2:
                flops = 2 * a[-2] * a[-1] * b[-1]
        else:
            flops = sum(r.elements() for r in regions)
        nbytes = sum(r.elements() for r in regions)
        if wreg is not None:
            nbytes += wreg.elements()
        self._event("compute", op=kind,
                    chunk=wreg.label() if wreg is not None else None,
                    flops=flops, nbytes=nbytes)


class FlightCapture(FlightSink):
    """Per-rank stream capture for the record-mode harness."""

    def __init__(self, rank: int):
        self.rank = int(rank)
        self.events: list[FlightEvent] = []

    def _emit(self, ev: FlightEvent) -> None:
        self.events.append(ev)


_GLOBAL = FlightSink()


def active() -> FlightSink | None:
    """The sink ``lang.primitives`` should report to on this thread:
    an installed capture first, else the global ring when enabled (and
    not suppressed — measurement sweeps stay out of the ring)."""
    cap = getattr(_tls, "cap", None)
    if cap is not None:
        return cap
    return _GLOBAL if enabled() else None


@contextlib.contextmanager
def capture(rank: int):
    """Install a per-rank capture on this thread; yields it.  Nesting is
    refused — a nested capture would silently split one rank's stream."""
    if getattr(_tls, "cap", None) is not None:
        raise RuntimeError("flight captures do not nest")
    cap = FlightCapture(rank)
    _tls.cap = cap
    try:
        yield cap
    finally:
        _tls.cap = None


# ---------------------------------------------------------------------------
# global-ring markers (engine / comm entry points)


def mark_step(idx: int) -> None:
    """Serving-step boundary: tag subsequent events and prune the ring to
    the last ``keep_steps()`` steps.  ≈0 cost when the ring is off."""
    if not enabled():
        return
    with _lock:
        _state["step"] = int(idx)
        _ring.append(FlightEvent("step", _now_us(), -1, None, None, None,
                                 None, 0, 0, 0, "step", int(idx)))
        floor = int(idx) - keep_steps()
        while _ring and _ring[0].step <= floor:
            _ring.popleft()


def mark_collective(op: str, *, payload_bytes: int = 0, ranks: int = 0,
                    method: str | None = None) -> None:
    """Host-side collective dispatch marker (``obs.comm_call`` and the
    fused-op entries): the coarse event a timeout dump anchors on."""
    if not enabled():
        return
    _GLOBAL._event("collective", op=op, nbytes=payload_bytes, elems=ranks,
                   sem=method)


def feed_streams(family: str, streams) -> int:
    """Append recorded per-rank streams into the global ring behind a
    family marker (``collective`` event, ``op=family``) — the feeder
    the continuous-profiler harness (``tdt_lint --profile``, tests)
    uses to put deterministic record-mode traffic where the live drain
    will find it.  Events are COPIED with the current step stamp: fresh
    identities (the profiler's drain cursor is identity-based) and
    correct ring pruning.  Returns the appended event count; 0 when the
    ring is off."""
    if not enabled():
        return 0
    mark_collective(family, ranks=len(streams))
    count = 1
    with _lock:
        step = _state["step"]
        for evs in streams:
            for ev in evs:
                _ring.append(FlightEvent(
                    ev.kind, _now_us(), ev.rank, ev.sem, ev.sem2,
                    ev.chunk, ev.peer, ev.elems, ev.flops, ev.bytes,
                    ev.op, step))
                count += 1
    return count


def recent(n: int | None = None) -> list[FlightEvent]:
    """The global ring's newest ``n`` events (all when None), oldest
    first."""
    evs = list(_ring)
    return evs if n is None else evs[-int(n):]


def recent_lines(n: int = 24) -> tuple[str, ...]:
    return tuple(ev.describe() for ev in recent(n))


def clear() -> None:
    with _lock:
        _ring.clear()
        _state["step"] = 0


# ---------------------------------------------------------------------------
# deterministic record-mode harness


def record_case(case) -> list[list[FlightEvent]]:
    """Record every rank of an ``analysis.registry.KernelCase`` with a
    flight capture installed — the same symbolic execution the protocol
    verifier runs, with the flight stream captured alongside.  Returns
    one event list per rank."""
    from ..analysis.record import coords_of, recording

    axes = getattr(case, "axes", None) or (("tp", case.n),)
    streams: list[list[FlightEvent]] = []
    for rank in range(case.n):
        _, thunk = case.make(rank)
        with recording(axes, coords_of(axes, rank)):
            with capture(rank) as cap:
                thunk()
        streams.append(cap.events)
    return streams


def record_family(family: str, n: int, *, variant: str | None = None):
    """Record the first (or ``variant``-matching) registry case of
    ``family`` at ``n`` ranks.  Returns ``(case_name, streams)``."""
    from ..analysis.registry import cases_for

    cases = cases_for(family, n)
    if variant:
        hits = [c for c in cases if variant in c.name]
        if not hits:
            raise ValueError(
                f"no {family} case matches variant {variant!r}; "
                f"available: {[c.name for c in cases]}"
            )
        cases = hits
    case = cases[0]
    return case.name, record_case(case)


def save_streams(name: str, streams, path: str) -> str:
    """Persist per-rank streams as JSON (``obs_report.py --timeline`` can
    reload them; the golden tests pin the format)."""
    with open(path, "w") as f:
        json.dump({
            "kernel": name, "n": len(streams),
            "streams": [[ev.to_dict() for ev in evs] for evs in streams],
        }, f, separators=(",", ":"))
    return path


def load_streams(path: str):
    """Inverse of :func:`save_streams`; returns ``(name, streams)``."""
    with open(path) as f:
        data = json.load(f)
    streams = [[FlightEvent.from_dict(d) for d in evs]
               for evs in data["streams"]]
    return data.get("kernel", "?"), streams
