"""Runtime observability: metrics registry, span tracing, exporters.

The reference framework proves its overlap claims with one-off profiler
charts; a production serving system needs the overlap *continuously
measured* (T3, arxiv 2401.16677: fine-grained tracking of the
compute/collective interleave is the enabler for overlap optimization).
This package is that layer:

- ``obs.registry``  counters / gauges / histograms (process-local,
  thread-safe, zero-dep) — written by the engine, collectives, autotuner
  and ``core.utils`` timers.
- ``obs.tracing``   ``span(...)`` wall-time events exporting Chrome-trace
  JSON that ``tools.trace_merge`` merges across hosts.
- ``obs.export``    JSONL append, Prometheus text format, summary table.
- ``obs.report``    the derived overlap-efficiency report
  (``scripts/obs_report.py``): per-step comm-exposed vs compute time.
- ``obs.serve_stats``  live serving telemetry: streaming quantile
  sketches (1% relative error) + windowed rates, fed by the engine and
  the comm entry points.
- ``obs.request_trace``  the per-request distributed trace plane
  (``TDT_TRACE=1``): gapless cross-tier span chains, the SLO
  attributor, p99 exemplars, the retained-trace ring.
- ``obs.continuous``  the continuous overlap profiler
  (``TDT_PROFILE=1``): per-step incremental flight-ring drain into
  windowed per-(family x topology x tier) SOL / exposed-wait rollups
  with a bounded on-disk time-series.
- ``obs.anomaly``   live-vs-baseline comparison of profiler windows
  against the committed-bench healthy bands (``obs.history`` — one
  band implementation); breaches surface in ``health()`` and advise
  the AdmissionGovernor.
- ``obs.decisions``  the fleet control-decision ledger
  (``TDT_FLEET_OBS=1``): every FleetRouter actuation recorded with its
  telemetry inputs verbatim, ring + rotated-JSONL retained, the kind
  axis golden-pinned by ``analysis.completeness``.
- ``obs.fleet_stats``  cross-replica telemetry federation + fleet-scope
  anomaly detection (``TDT_FLEET_OBS=1``): per-replica tee collectors
  merging losslessly into the fleet view, imbalance/skew gauges, and
  band breaches that carry the ledger decisions from their window.
- ``obs.server``    the ``TDT_OBS_HTTP`` endpoint: ``/metrics``,
  ``/healthz``, ``/debug/flight``, ``/debug/timeline``,
  ``/debug/profile``, ``/debug/diff``, ``/debug/fleet``.
- ``obs.history``   the perf-trajectory sentinel over the committed
  ``BENCH_r*`` rounds (``scripts/bench_history.py``).
- ``obs.diff``      regression forensics: differential root-cause
  attribution between any two comparable captures (profiler windows,
  bench rounds, trace cohorts, fleet replicas) — ranked causal
  decomposition with an exactness contract, wired into every
  detection site (``docs/observability.md``).

Everything is OFF by default and gated by ``TDT_OBS=1`` (or
:func:`enable`); a disabled call site costs one cached-bool check, so the
instrumented hot paths (``bench.py`` loops, the serve loop) are unchanged
when observability is off.  Metric names and conventions are documented
in ``docs/observability.md``.
"""

from __future__ import annotations

import contextlib
import threading

from . import (
    anomaly, continuous, costs, decisions, diff, export, flight,
    fleet_stats, history, registry, report, request_trace, serve_stats,
    timeline, tracing,
)


def __getattr__(name: str):
    # obs.server pulls the http.server/socketserver import chain —
    # loaded lazily so every `from .. import obs` in the comm hot paths
    # keeps the advertised near-zero cost-when-off.  importlib (NOT
    # `from . import server`, whose fromlist handling getattrs the
    # package first and would recurse here) imports the submodule and
    # binds the package attribute, so __getattr__ runs at most once.
    if name == "server":
        import importlib

        return importlib.import_module(".server", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from .export import (
    parse_prometheus,
    read_jsonl,
    summary_table,
    to_prometheus,
    write_jsonl,
)
from .registry import (
    DEFAULT_BYTES_BUCKETS,
    DEFAULT_LATENCY_BUCKETS_MS,
    REGISTRY,
    Registry,
)
from .tracing import instant, span

__all__ = [
    "DEFAULT_BYTES_BUCKETS", "DEFAULT_LATENCY_BUCKETS_MS", "REGISTRY",
    "Registry", "anomaly", "comm_call", "continuous", "costs", "counter",
    "decisions", "dump_jsonl",
    "dump_prometheus", "enable", "enabled", "fleet_stats", "flight",
    "gauge", "histogram",
    "history", "instant", "observe_timer", "parse_prometheus", "read_jsonl",
    "record_collective", "request_trace", "serve_stats", "server", "span",
    "summary",
    "summary_table", "suppress", "suppressed_thunk", "timeline",
    "to_prometheus", "write_jsonl",
]


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_OBS")


# Cached so the per-call cost at a disabled site is one global load +
# one function call; re-read the env only through enable(None).
_ENABLED = _env_enabled()

_tls = threading.local()


def _suppressed() -> bool:
    return getattr(_tls, "depth", 0) > 0


def enabled() -> bool:
    """Whether instrumentation records (``TDT_OBS=1`` or :func:`enable`,
    and not inside a :func:`suppress` block on this thread)."""
    return _ENABLED and not _suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn recording on/off at runtime; ``None`` re-reads ``TDT_OBS``.
    Returns the new state."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


@contextlib.contextmanager
def suppress():
    """Pause recording on this thread.  Used around measurement-only
    traffic — autotune sweeps re-invoke the instrumented comm entry
    points hundreds of times per candidate, and ``Engine.serve``'s
    compile warmup is not a serving step — so counters, spans, and the
    overlap report describe REAL traffic only."""
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _tls.depth -= 1


def suppressed_thunk(f):
    """Wrap a measurement thunk so everything it records is suppressed
    (``tune.autotuner`` wraps each candidate thunk once; all later timed
    invocations stay silent)."""
    def g():
        with suppress():
            return f()
    return g


# -- thin registry front-door (the names call sites use) -------------------

def counter(name: str, /, **labels) -> registry.Counter:
    return REGISTRY.counter(name, **labels)


def gauge(name: str, /, **labels) -> registry.Gauge:
    return REGISTRY.gauge(name, **labels)


def histogram(name: str, buckets=DEFAULT_LATENCY_BUCKETS_MS,
              /, **labels) -> registry.Histogram:
    return REGISTRY.histogram(name, buckets, **labels)


def summary() -> str:
    """Human-readable table of every recorded metric."""
    return summary_table(REGISTRY)


def dump_jsonl(path: str, *, extra: dict | None = None) -> int:
    """Append a snapshot of the global registry to ``path`` (JSONL)."""
    return write_jsonl(REGISTRY, path, extra=extra)


def dump_prometheus() -> str:
    """Prometheus text exposition of the global registry."""
    return to_prometheus(REGISTRY)


# -- shared instrumentation helpers ----------------------------------------

def observe_timer(name: str, ms: float) -> None:
    """Route a ``core.utils.timer`` / ``perf_func`` measurement into the
    registry (``timer_ms{name=...}``).  Call sites gate on
    :func:`enabled` themselves; this also no-ops when disabled so direct
    callers stay safe."""
    if not enabled():
        return
    REGISTRY.histogram("timer_ms", DEFAULT_LATENCY_BUCKETS_MS,
                       name=name).observe(ms)


def record_collective(op: str, *, payload_bytes: int, wire_bytes: int,
                      chunks: int, method: str) -> None:
    """One collective invocation, from the host entry points in ``comm/``.

    ``payload_bytes``: the local input shard; ``wire_bytes``: the
    per-rank wire estimate for the selected method (the formulas are in
    ``docs/observability.md``); ``chunks``: ring steps / chunk count.
    Eager calls only — traced (jit) calls run this Python once at trace
    time, so the entry points skip recording for tracer inputs.
    """
    if not enabled():
        return
    REGISTRY.counter("comm_calls", op=op, method=method).inc()
    REGISTRY.counter("comm_payload_bytes", op=op, method=method).inc(
        payload_bytes)
    REGISTRY.counter("comm_wire_bytes", op=op, method=method).inc(wire_bytes)
    REGISTRY.counter("comm_chunks", op=op, method=method).inc(chunks)
    REGISTRY.histogram("comm_payload_bytes_hist", DEFAULT_BYTES_BUCKETS,
                       op=op).observe(payload_bytes)
    # live telemetry plane: per-collective windowed wire-byte rate
    # (obs.serve_stats, scraped via /metrics — docs/observability.md
    # "Live telemetry")
    serve_stats.STATS.observe_collective(op, wire_bytes=wire_bytes)


def comm_call(op: str, thunk, *, payload_bytes: int, wire_bytes: int,
              chunks: int, method: str, ranks: int):
    """The one shared shape of a comm entry point's instrumentation:
    record the call's counters, mark the flight ring, then run ``thunk``
    under a ``comm`` span.  Call sites gate on :func:`enabled` OR
    ``flight.enabled()`` plus non-tracer inputs and compute the
    per-method byte formulas (``docs/observability.md``)."""
    record_collective(op, payload_bytes=payload_bytes,
                      wire_bytes=wire_bytes, chunks=chunks, method=method)
    # flight ring (TDT_FLIGHT=1): the host-side dispatch marker a timeout
    # dump anchors on — no-op when the ring is off
    flight.mark_collective(op, payload_bytes=payload_bytes, ranks=ranks,
                           method=method)
    with tracing.span(op, "comm", method=method, bytes=payload_bytes,
                      ranks=ranks):
        return thunk()
