"""Differential root-cause attribution between two comparable captures.

Six detection layers now end at "metric X fell out of band" — the
trend sentinel (``obs.history``), the live window comparator
(``obs.anomaly``), the fleet bands (``obs.fleet_stats``) — and every
one of them leaves the operator to hand-correlate timelines, traces,
and ledgers to learn WHY.  This module closes the loop from *detected*
to *explained*: give it any two comparable captures and it returns a
RANKED causal decomposition of the delta.

Four pairings, one engine:

=========  ==========================================================
pairing    captures
=========  ==========================================================
windows    two rotated profiler windows (:func:`diff_windows` — the
           live breach vs the band-representative healthy window the
           profiler retains; see :func:`baseline_window`)
rounds     two committed bench rounds (:func:`diff_rounds`, via the
           ``obs.history`` local streams)
cohorts    two trace cohorts (:func:`diff_cohorts` — e.g. the p99
           exemplar vs a p50 cohort, span-aligned waterfall diff)
replicas   two fleet replicas (:func:`diff_replicas`, over
           ``fleet_stats.ReplicaStats`` sketches)
=========  ==========================================================

Each ranked term names the phase (the PR-13 attributor vocabulary:
queue / prefill / handoff / decode / preempted), the (collective
family x topology x tier) rollup, the dominant (semaphore, chunk,
peer) stall triple, the exposed-vs-overlapped split of the delta, and
a resolving exemplar trace id — whichever of those the pairing's
captures carry.

Exactness contract (the PR-13 ``gap_ms`` discipline): for the additive
pairings (windows, cohorts) the ranked term deltas plus the reported
``residual`` sum to ``total_delta`` EXACTLY — ``residual`` is defined
as the closing difference, and ``exact`` asserts it stays within the
float-rounding budget of the captures' own rounded fields
(:data:`EXACT_TOL_PER_TERM` per contributing key).  The metric-set
pairings (rounds, replicas) have no cross-metric additive total —
each term IS one metric's own delta, ``total_delta`` is ``None``, and
the contract binds per term trivially.

Every number is read from the existing machinery: the window terms are
the credit-replay sums ``Rollup`` already accumulated
(``obs.timeline`` -> ``obs.continuous``), the cohort terms are
``request_trace.attribute_request`` phase budgets, bands come from
``history.healthy_band``.  Nothing is re-derived here — this module
subtracts and ranks, it never re-implements an attribution.

``tdt_lint --regress`` runs :func:`selftest` both directions: an
identical-capture diff must rank nothing, and a wire-inflated replay
must attribute the delta to the injected family/phase/stall with a
resolving exemplar and an exact residual.
"""

from __future__ import annotations

from . import history

# a delta this small is "no change": it never ranks (the
# identical-capture direction of the selftest depends on this)
ZERO_TOL = 1e-9

# per-contributing-key rounding budget for the residual: window
# captures round ``*_us`` fields at 3 decimals (``Rollup.to_dict``)
# and totals at 6 (``ContinuousProfiler._totals``), so each key can
# contribute up to ~5e-7 ms of closing dust
EXACT_TOL_PER_TERM = 1e-6

# window-total metrics that ARE additive over rollups — the substrate
# a window diff decomposes.  A non-additive breach metric (pct_sol,
# overlap_hidden_pct) is recorded as ``observed`` but decomposed on
# the exposed_ms substrate: exposed wait is where the delta lives.
_SUBSTRATES = {
    "exposed_ms": "exposed_us",
    "wire_ms": "wire_us",
    "compute_ms": "compute_us",
}

# canonical phase order for cohort terms (request_trace.PHASE_OF
# vocabulary); unknown phases append after, in first-seen order
_PHASE_ORDER = ("queue", "prefill", "handoff", "decode", "preempted")

# which serving phase each fleet sketch measures (None = whole-request)
_SKETCH_PHASE = {
    "prefill_ms": "prefill",
    "decode_ms_per_token": "decode",
    "handoff_ms": "handoff",
}


# ---------------------------------------------------------------------------
# shared term plumbing


def _term(**kw) -> dict:
    out = {
        "rank": None,
        "metric": None,
        "phase": None,
        "family": None,
        "topology": None,
        "tier": None,
        "delta": 0.0,
        "unit": "ms",
        "exposed_delta_ms": None,
        "overlapped_delta_ms": None,
        "stall": None,
        "exemplar": None,
        "pct_of_total": None,
        "summary": "",
    }
    out.update(kw)
    return out


def _close(terms: list[dict], total_delta: float | None,
           sort_key=None) -> tuple[list[dict], float, bool]:
    """Drop no-change terms, rank the rest, and close the additive
    identity: ``sum(kept deltas) + residual == total_delta`` holds
    EXACTLY (residual is defined as that difference)."""
    n_keys = max(1, len(terms))
    kept = [t for t in terms if abs(t["delta"]) > ZERO_TOL]
    kept.sort(key=sort_key or (lambda t: abs(t["delta"])), reverse=True)
    for i, t in enumerate(kept):
        t["rank"] = i + 1
        if total_delta is not None and abs(total_delta) > ZERO_TOL:
            t["pct_of_total"] = round(100.0 * t["delta"] / total_delta, 1)
    if total_delta is None:
        return kept, 0.0, True
    residual = total_delta - sum(t["delta"] for t in kept)
    return kept, residual, abs(residual) <= EXACT_TOL_PER_TERM * n_keys


def _result(kind: str, a, b, *, metric: str, unit: str,
            total_delta: float | None, terms: list[dict],
            residual: float, exact: bool, exemplar=None,
            observed=None) -> dict:
    out = {
        "kind": kind,
        "a": a,
        "b": b,
        "metric": metric,
        "unit": unit,
        "total_delta": total_delta,
        "terms": terms,
        "residual": residual,
        "exact": exact,
        "exemplar": exemplar,
    }
    if observed is not None:
        out["observed"] = observed
    out["summary"] = attribution_summary(out)
    return out


def attribution_summary(d: dict) -> str:
    """The one-line explanation a WARN line / event summary carries:
    the total move plus the top-ranked term."""
    head = d["metric"]
    if d.get("total_delta") is not None:
        head += f" {d['total_delta']:+.3f} {d['unit']}".rstrip()
    terms = d.get("terms") or []
    if not terms:
        return f"{head}: no attributable delta"
    t = terms[0]
    where = t["metric"] or ""
    if t["family"]:
        where = f"{t['family']} x {t['topology']} x {t['tier']}"
    elif t["phase"]:
        where = f"phase {t['phase']}"
    s = f"{head}: #1 {where} ({t['delta']:+.3f} {t['unit']}".rstrip()
    if t.get("pct_of_total") is not None:
        s += f", {t['pct_of_total']:g}% of delta"
    s += ")"
    if t["stall"]:
        sem, chunk, peer = t["stall"][:3]
        s += f"; stall sem={sem} chunk={chunk} peer={peer}"
    ex = t["exemplar"] or d.get("exemplar")
    if ex:
        s += f"; exemplar {ex}"
    return s


# ---------------------------------------------------------------------------
# pairing 1: two profiler windows


def diff_windows(a: dict, b: dict, *, metric: str = "exposed_ms",
                 exemplar: str | None = None) -> dict:
    """Ranked (family x topology x tier) decomposition of window ``b``
    minus window ``a`` (baseline first — positive deltas are growth in
    the live window).

    ``metric`` names the breached window-total; the decomposition runs
    on its additive substrate (``exposed_ms`` unless the metric is
    itself one of ``wire_ms`` / ``compute_ms``).  Every term's numbers
    are the credit-replay ``Rollup`` sums the windows already carry —
    this function only subtracts and ranks.  The tier axis IS the
    serving-phase vocabulary (the scheduler feeds ``on_step`` per
    tier), so each term's ``phase`` is its rollup tier."""
    substrate = metric if metric in _SUBSTRATES else "exposed_ms"
    us_field = _SUBSTRATES[substrate]

    def _key(r):
        return (r.get("family", "?"), r.get("topology", "?"),
                r.get("tier", "?"))

    ra = {_key(r): r for r in (a.get("rollups") or [])}
    rb = {_key(r): r for r in (b.get("rollups") or [])}
    keys = list(ra) + [k for k in rb if k not in ra]
    terms = []
    for key in keys:
        xa = ra.get(key) or {}
        xb = rb.get(key) or {}
        delta = (float(xb.get(us_field, 0.0))
                 - float(xa.get(us_field, 0.0))) / 1e3
        exposed_d = (float(xb.get("exposed_us", 0.0))
                     - float(xa.get("exposed_us", 0.0))) / 1e3
        hidden_b = float(xb.get("wire_us", 0.0)) \
            - float(xb.get("exposed_us", 0.0))
        hidden_a = float(xa.get("wire_us", 0.0)) \
            - float(xa.get("exposed_us", 0.0))
        worse = xb if delta >= 0 else xa
        stall = worse.get("dominant_stall") or \
            (xb or xa).get("dominant_stall")
        fam, topo, tier = key
        terms.append(_term(
            metric=f"{fam}/{topo}/{tier}", phase=tier, family=fam,
            topology=topo, tier=tier, delta=delta, unit="ms",
            exposed_delta_ms=exposed_d,
            overlapped_delta_ms=(hidden_b - hidden_a) / 1e3,
            stall=tuple(stall) if stall else None,
            exemplar=exemplar,
            summary=(f"{fam} x {topo} x {tier}: {delta:+.3f} ms "
                     f"({substrate})"),
        ))
    ta = a.get("totals") or {}
    tb = b.get("totals") or {}
    total_delta = float(tb.get(substrate, 0.0) or 0.0) \
        - float(ta.get(substrate, 0.0) or 0.0)
    kept, residual, exact = _close(terms, total_delta)
    return _result(
        "windows",
        {"window": a.get("window"), "step_end": a.get("step_end")},
        {"window": b.get("window"), "step_end": b.get("step_end")},
        metric=substrate if metric in _SUBSTRATES else metric,
        unit="ms", total_delta=total_delta, terms=kept,
        residual=residual, exact=exact, exemplar=exemplar,
        observed={"metric": metric, "a": ta.get(metric),
                  "b": tb.get(metric)},
    )


def baseline_window(windows: list[dict], *,
                    metric: str = "exposed_ms") -> dict | None:
    """The band-representative healthy window: among retained PRIOR
    windows that did not themselves breach, the one whose ``metric``
    total sits nearest the healthy-band median —
    ``history.healthy_band`` is the ONE band implementation, reused
    here for representativeness, never re-derived."""
    cand = []
    for w in windows:
        if w.get("anomalies"):
            continue
        v = (w.get("totals") or {}).get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            cand.append((float(v), w))
    if not cand:
        return None
    band = history.healthy_band([v for v, _ in cand], "lower")
    target = band.median if band is not None else cand[0][0]
    return min(cand, key=lambda p: abs(p[0] - target))[1]


# ---------------------------------------------------------------------------
# pairing 2: two bench rounds


def diff_rounds(a, b) -> dict:
    """Per-metric regression ranking between two committed bench
    rounds (``history.Round``), newest second.  Terms are ranked by
    worse-direction drift under each metric's ``direction_for``
    classification; there is no cross-metric additive total, so the
    exactness contract binds per term (each term IS one metric's own
    delta)."""

    def _vals(rnd):
        out = {}
        for rec in rnd.metrics:
            name, v = rec.get("metric"), rec.get("value")
            if (not name or rec.get("interpret")
                    or not isinstance(v, (int, float))
                    or isinstance(v, bool)):
                continue
            out[name] = (float(v), str(rec.get("unit", "")))
        return out

    ma, mb = _vals(a), _vals(b)
    terms = []
    for name in sorted(set(ma) & set(mb)):
        (va, unit), (vb, _) = ma[name], mb[name]
        direction = history.direction_for(name, unit)
        if direction == "exact":
            drift = 0.0 if va == vb else 1.0
        else:
            drift = history._drift_pct(direction, vb, va)
        terms.append(_term(
            metric=name, delta=vb - va, unit=unit,
            summary=(f"{name}: {va:g} -> {vb:g} {unit} "
                     f"({100 * drift:+.1f}% "
                     f"{'worse' if drift > 0 else 'better'}, "
                     f"{direction})"),
            # drift rides the term for ranking and for the WARN notes
            pct_of_total=None,
        ))
        terms[-1]["drift_pct"] = drift
        terms[-1]["direction"] = direction
    kept, residual, exact = _close(
        terms, None, sort_key=lambda t: t["drift_pct"])
    kept = [t for t in kept if abs(t["drift_pct"]) > ZERO_TOL]
    for i, t in enumerate(kept):
        t["rank"] = i + 1
    return _result(
        "rounds", {"round": a.round}, {"round": b.round},
        metric=f"r{a.round}->r{b.round}", unit="", total_delta=None,
        terms=kept, residual=residual, exact=exact,
    )


def rounds_attribution(trajectories: dict, metric: str, *,
                       top: int = 3, min_drift: float = 0.02
                       ) -> str | None:
    """The round-over-round note a trend WARN line carries: which
    OTHER metrics co-moved in their worse direction between the warned
    metric's last two rounds.  A co-regressed overhead or latency
    metric is the first causal lead; None when nothing co-moved (the
    regression is isolated — also a lead)."""
    tr = trajectories.get(metric)
    if tr is None or len(tr.draws) < 2:
        return None
    r_prev, r_new = tr.draws[-2].round, tr.draws[-1].round
    movers = []
    for name, other in trajectories.items():
        if name == metric or other.direction == "exact" \
                or len(other.draws) < 2:
            continue
        d_new, d_prev = other.draws[-1], other.draws[-2]
        if d_new.round != r_new or d_prev.round != r_prev:
            continue
        drift = history._drift_pct(other.direction,
                                   d_new.value, d_prev.value)
        if drift > min_drift:
            movers.append((drift, name))
    if not movers:
        return None
    movers.sort(reverse=True)
    note = ", ".join(f"{n} ({100 * d:.0f}% worse)"
                     for d, n in movers[:top])
    return f" | co-regressed r{r_prev}->r{r_new}: {note}"


# ---------------------------------------------------------------------------
# pairing 3: two trace cohorts


def diff_cohorts(a: list, b: list, *, label_a: str = "cohort-a",
                 label_b: str = "cohort-b") -> dict:
    """Span-aligned phase diff of two trace cohorts: per-phase
    mean-exposed deltas (``attribute_request`` budgets — the ONE phase
    arithmetic) plus a chain-gap term, closing to the mean e2e delta
    exactly (a trace's phases partition [submit, terminal]:
    ``e2e_ms == sum(exposed) + gap_ms``).  The resolving exemplar is
    the slowest trace of the second cohort."""
    from . import request_trace as rtrace

    if not a or not b:
        raise ValueError("diff_cohorts: both cohorts must be non-empty")

    def _mean(traces):
        ph: dict[str, list[float]] = {}
        e2e = gap = 0.0
        worst = None
        for t in traces:
            att = rtrace.attribute_request(t)
            e2e += att["e2e_ms"]
            gap += att["gap_ms"]
            if worst is None or att["e2e_ms"] > worst[0]:
                worst = (att["e2e_ms"], att["trace_id"])
            for p, d in att["phases"].items():
                cur = ph.setdefault(p, [0.0, 0.0])
                cur[0] += d["exposed_ms"]
                cur[1] += d["overlapped_ms"]
        n = float(len(traces))
        return ({p: (e / n, o / n) for p, (e, o) in ph.items()},
                e2e / n, gap / n, worst[1] if worst else None)

    pa, e2e_a, gap_a, _ = _mean(a)
    pb, e2e_b, gap_b, exemplar = _mean(b)
    phases = [p for p in _PHASE_ORDER if p in pa or p in pb]
    phases += [p for p in list(pa) + list(pb)
               if p not in phases and (p in pa or p in pb)]
    seen = set()
    phases = [p for p in phases if not (p in seen or seen.add(p))]
    terms = []
    for p in phases:
        ea, oa = pa.get(p, (0.0, 0.0))
        eb, ob = pb.get(p, (0.0, 0.0))
        terms.append(_term(
            metric=f"phase/{p}", phase=p, delta=eb - ea, unit="ms",
            exposed_delta_ms=eb - ea, overlapped_delta_ms=ob - oa,
            exemplar=exemplar,
            summary=(f"phase {p}: exposed {eb - ea:+.3f} ms, "
                     f"overlapped {ob - oa:+.3f} ms"),
        ))
    if abs(gap_b - gap_a) > ZERO_TOL:
        terms.append(_term(
            metric="phase/(chain-gap)", phase="(chain-gap)",
            delta=gap_b - gap_a, unit="ms", exemplar=exemplar,
            summary=f"chain gap: {gap_b - gap_a:+.3f} ms",
        ))
    total_delta = e2e_b - e2e_a
    kept, residual, exact = _close(terms, total_delta)
    return _result(
        "cohorts", {"label": label_a, "n": len(a), "e2e_ms": e2e_a},
        {"label": label_b, "n": len(b), "e2e_ms": e2e_b},
        metric="e2e_ms", unit="ms", total_delta=total_delta,
        terms=kept, residual=residual, exact=exact, exemplar=exemplar,
    )


def diff_traces(a, b) -> dict:
    """Two single traces as one-element cohorts (the ``--request p99``
    exemplar-vs-p50 view builds on :func:`diff_cohorts` directly)."""
    return diff_cohorts([a], [b], label_a=a.trace_id, label_b=b.trace_id)


# ---------------------------------------------------------------------------
# pairing 4: two fleet replicas


def diff_replicas(a, b, *, quantile: float = 0.99) -> dict:
    """Per-sketch quantile deltas between two replicas'
    ``ReplicaStats`` (baseline first).  All fleet sketches are
    latencies in ms, so terms rank by absolute delta; each term's
    exemplar is the worse side's quantile exemplar — trace ids survive
    the federation union merge (pinned by test), so the id resolves
    against the ring / a trace dump."""
    from . import fleet_stats

    terms = []
    for name in fleet_stats.SKETCH_NAMES:
        sa, sb = getattr(a, name, None), getattr(b, name, None)
        if sa is None or sb is None:
            continue
        va, vb = float(sa.quantile(quantile)), float(sb.quantile(quantile))
        if va == 0.0 and vb == 0.0:
            continue
        delta = vb - va
        worse = sb if delta >= 0 else sa
        exemplar = worse.exemplar(quantile)
        label = f"{name}_p{int(round(quantile * 100))}"
        terms.append(_term(
            metric=label, phase=_SKETCH_PHASE.get(name),
            delta=delta, unit="ms", exemplar=exemplar,
            summary=f"{label}: {va:g} -> {vb:g} ms ({delta:+.3f})",
        ))
    kept, residual, exact = _close(terms, None)
    ida = getattr(a, "replica_id", "a")
    idb = getattr(b, "replica_id", "b")
    return _result(
        "replicas", {"replica": ida}, {"replica": idb},
        metric=f"{ida}->{idb}", unit="ms", total_delta=None,
        terms=kept, residual=residual, exact=exact,
        exemplar=kept[0]["exemplar"] if kept else None,
    )


# ---------------------------------------------------------------------------
# rendering (obs_report --diff / --request p99)


def format_diff(d: dict) -> str:
    """The operator view: header, ranked terms, closing residual."""
    lines = [f"regression forensics [{d['kind']}]  "
             f"{d['a']} -> {d['b']}"]
    if d.get("observed"):
        o = d["observed"]
        lines.append(f"  observed {o['metric']}: "
                     f"{o.get('a')} -> {o.get('b')}")
    if d.get("total_delta") is not None:
        lines.append(f"  total delta: {d['total_delta']:+.6f} "
                     f"{d['unit']}".rstrip())
    if not d["terms"]:
        lines.append("  (no attributable delta — captures are "
                     "equivalent)")
    for t in d["terms"]:
        row = f"  #{t['rank']:<2d} {t['summary']}"
        if t["exposed_delta_ms"] is not None and t["family"]:
            row += (f" [exposed {t['exposed_delta_ms']:+.3f} / "
                    f"overlapped {t['overlapped_delta_ms']:+.3f} ms]")
        if t["stall"]:
            sem, chunk, peer = t["stall"][:3]
            row += f" stall(sem={sem}, chunk={chunk}, peer={peer})"
        if t["exemplar"]:
            row += f" exemplar={t['exemplar']}"
        lines.append(row)
    if d.get("total_delta") is not None:
        lines.append(f"  residual: {d['residual']:+.9f} {d['unit']} "
                     f"({'exact' if d['exact'] else 'NOT EXACT'})"
                     .rstrip())
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# selftest (tdt_lint --regress + tier-1)


def _synthetic_trace(trace_id: str, decode_ms: float) -> object:
    """A minimal closed trace with fixed phase budgets (model-clock
    style determinism: literal timestamps, no wall reads)."""
    from . import request_trace as rtrace

    t0 = 1_000_000.0
    q, pf = 250.0, 2_000.0
    spans = [
        {"name": "queue_wait", "tier": "prefill", "t0_us": t0,
         "t1_us": t0 + q, "tags": {}},
        {"name": "prefill", "tier": "prefill", "t0_us": t0 + q,
         "t1_us": t0 + q + pf, "tags": {}},
        {"name": "decode", "tier": "decode", "t0_us": t0 + q + pf,
         "t1_us": t0 + q + pf + decode_ms * 1e3, "tags": {}},
    ]
    return rtrace.from_dict({
        "trace_id": trace_id, "req_id": 0, "state": "completed",
        "t0_us": t0, "first_token_us": t0 + q + pf,
        "dropped_spans": 0, "spans": spans, "events": [],
    })


def selftest(seed: int = 0) -> list[str]:
    """Both-direction regress check over a REAL recorded capture run
    through the REAL profiler path (the ``obs.anomaly`` selftest
    harness): an identical-capture diff must rank NOTHING, and the
    65536x wire-inflated replay must attribute the delta to the
    injected family ("allgather"), the fed phase/tier, and a
    (sem, chunk, peer) stall triple, with a resolving exemplar trace
    id and an exact residual.  A planted trace-cohort slowdown must
    likewise attribute to the planted phase.  Perturbs the flight ring
    and serve stats; callers reset.  Returns problems (empty = pass)."""
    from . import anomaly, continuous, flight, serve_stats
    from . import request_trace as rtrace

    problems: list[str] = []
    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    flight.enable(True)
    continuous.enable(True)
    tid = f"req-regress-selftest-{seed}"
    try:
        # a resolving exemplar: the id is both the p99 bucket exemplar
        # AND a retained ring trace, so the attribution's trace id
        # dereferences to a real waterfall
        serve_stats.STATS.reset()
        serve_stats.STATS.request_ms.observe(123.0, exemplar=tid)
        rtrace.RING.retire(_synthetic_trace(tid, decode_ms=40.0))
        _, streams = flight.record_family("allgather", 2)

        def window_of(streams_):
            prof = continuous.ContinuousProfiler(window_steps=1,
                                                 out_dir="")
            flight.clear()
            flight.feed_streams("allgather", streams_)
            prof.on_step("decode", 1)
            return prof.last_window()

        healthy = window_of(streams)
        if healthy is None or not healthy["totals"]["episodes"]:
            return ["regress selftest: the recorded capture produced "
                    "no profiler window"]

        # direction 1: identical captures must rank nothing
        same = diff_windows(healthy, healthy)
        if same["terms"]:
            problems.append(
                f"regress selftest: identical-capture diff ranked "
                f"{[t['metric'] for t in same['terms']]} — a clean "
                f"pair must produce no terms")
        if not same["exact"] or same["residual"] != 0.0:
            problems.append(
                f"regress selftest: identical-capture residual "
                f"{same['residual']!r} != 0")

        # direction 2: the seeded regression must be attributed to the
        # injected family/phase/stall, exactly
        bad = window_of(anomaly._inflate_wire(streams, 1 << 16))
        d = diff_windows(healthy, bad,
                         exemplar=serve_stats.STATS.request_ms
                         .exemplar(0.99))
        if not d["terms"]:
            problems.append("regress selftest: the 65536x wire "
                            "inflation produced no ranked terms")
        else:
            top = d["terms"][0]
            if top["family"] != "allgather":
                problems.append(
                    f"regress selftest: top term names family "
                    f"{top['family']!r}, not the injected 'allgather'")
            if top["phase"] != "decode":
                problems.append(
                    f"regress selftest: top term names phase "
                    f"{top['phase']!r}, not the fed 'decode' tier")
            if not top["stall"] or top["stall"][0] is None:
                problems.append(
                    "regress selftest: top term carries no dominant "
                    "(sem, chunk, peer) stall triple")
            if top["delta"] <= 0:
                problems.append(
                    f"regress selftest: injected inflation attributed "
                    f"a non-positive delta ({top['delta']:g} ms)")
            ex = top["exemplar"] or d["exemplar"]
            if not ex:
                problems.append(
                    "regress selftest: attribution names no exemplar")
            elif rtrace.RING.get(ex) is None:
                problems.append(
                    f"regress selftest: exemplar {ex!r} does not "
                    f"resolve in the trace ring")
        if d["total_delta"] <= 0:
            problems.append(
                f"regress selftest: total delta "
                f"{d['total_delta']:g} ms — the inflated window must "
                f"grow the exposed substrate")
        if not d["exact"]:
            problems.append(
                f"regress selftest: residual {d['residual']:g} ms "
                f"breaks the exactness contract")

        # direction 2b: a planted cohort slowdown attributes to the
        # planted phase with the same exactness
        fast = _synthetic_trace(f"req-regress-p50-{seed}",
                                decode_ms=10.0)
        slow = _synthetic_trace(f"req-regress-p99-{seed}",
                                decode_ms=90.0)
        cd = diff_traces(fast, slow)
        if not cd["terms"] or cd["terms"][0]["phase"] != "decode":
            problems.append(
                f"regress selftest: planted decode slowdown "
                f"attributed to "
                f"{cd['terms'][0]['phase'] if cd['terms'] else None!r}")
        if not cd["exact"]:
            problems.append(
                f"regress selftest: cohort residual "
                f"{cd['residual']:g} ms breaks the exactness contract")
        same_c = diff_traces(fast, fast)
        if same_c["terms"]:
            problems.append(
                "regress selftest: identical-cohort diff ranked "
                "terms")
    finally:
        flight.clear()
        flight.enable(prev_flight)
        continuous.enable(prev_prof)
    return problems
