"""Cross-rank timeline reconstruction from flight-recorder streams.

The reference proves its overlap story by merging per-rank profiler
traces into one chrome timeline; this module does the same for the
PROTOCOL layer, and goes one step further: it *attributes* every rank's
stall time to the (semaphore, chunk, peer) it was waiting on.

Input: one flight-event stream per rank (``obs.flight.record_case`` for
the deterministic record-mode harness, or reloaded ``save_streams``
files).  The reconstruction is a credit-dataflow replay — the same
maximal-execution semantics as ``resilience.simulate`` — on a REAL-VALUED
model clock whose durations come from ``obs.costs`` and the
``tools.perf_model`` chip spec:

- a ``compute`` event takes ``launch + max(flops/MXU, bytes/HBM)``;
- a ``remote_copy``'s credits become consumable ``hop + bytes/ICI``
  after issue (the wire time);
- a wait completes at ``max(own clock, ready time of the credits it
  consumes)`` — the gap is that rank's **exposed wait**, attributed to
  the latest-arriving credit's (semaphore, chunk, producing rank);
- ``barrier`` events are a rendezvous: clocks join at the max
  (neighbor barriers are approximated as global — conservative, and
  exact for the single prologue barrier every kernel opens with).
  This is also what aligns per-rank clocks: recorded streams start at
  rank-local zero and the barrier join puts them on one global clock,
  the model-time analogue of :func:`align_clocks` for wall timestamps.

At registry example dims the reconstruction sits in the latency regime
(hop latency dominates byte time) — the columns are still exact model
time, and on real-shape streams the same arithmetic yields the
bandwidth picture.  ``pct_sol`` compares the reconstructed critical
path against the per-rank roofline ``max(compute, wire)`` — the
achieved-vs-SOL figure of ``scripts/obs_report.py --timeline``.

A truncated stream (partial ring buffer: the recorder dropped the
oldest events) reconstructs as far as the credits allow and reports the
unreplayable tail as ``pending`` instead of raising — the
dump-at-failure path must never turn a diagnosis into a crash.
"""

from __future__ import annotations

import dataclasses
from collections import deque

# model-time constants (us): ICI hop latency per transfer/signal, fixed
# per-pipeline-invocation launch cost, and the bookkeeping epsilon that
# keeps program order strict on the model clock
HOP_US = 1.0
LAUNCH_US = 0.5
EPS_US = 0.01


@dataclasses.dataclass(frozen=True)
class WaitAttribution:
    """One attributed stall: ``rank`` spent ``exposed_us`` blocked on
    ``sem`` waiting for ``chunk`` from ``source``."""

    rank: int
    kind: str                 # wait | wait_recv | wait_send
    sem: str | None
    chunk: str | None
    source: int | None        # producing rank of the latest credit
    exposed_us: float
    t_end_us: float

    def describe(self) -> str:
        s = (f"rank {self.rank} waited {self.exposed_us:.3f}us on "
             f"{self.sem or '?'}")
        if self.chunk:
            s += f" for chunk {self.chunk}"
        if self.source is not None:
            s += f" from rank {self.source}"
        return s


@dataclasses.dataclass(frozen=True)
class Interval:
    rank: int
    lane: str                 # protocol | wire
    kind: str
    label: str
    t0_us: float
    t1_us: float


@dataclasses.dataclass
class RankRow:
    rank: int
    compute_us: float = 0.0
    wire_us: float = 0.0
    exposed_us: float = 0.0
    barrier_us: float = 0.0
    finish_us: float = 0.0


@dataclasses.dataclass
class Timeline:
    kernel: str
    n: int
    rows: list[RankRow]
    waits: list[WaitAttribution]
    intervals: list[Interval]
    flows: list[tuple[Interval, float, int]]  # (wire interval, wait end, dst)
    critical_us: float
    skew_us: float
    sol_us: float
    stalled: bool = False
    pending: tuple[str, ...] = ()

    @property
    def pct_sol(self) -> float:
        """Achieved-vs-SOL: the roofline lower bound over the
        reconstructed critical path (clamped at 1.0 — the bound ignores
        protocol dependencies, so a latency-pipelined kernel can touch
        it but never beat it meaningfully)."""
        if self.critical_us <= 0:
            return 1.0
        return min(1.0, self.sol_us / self.critical_us)


@dataclasses.dataclass
class _Credit:
    amount: int
    ready: float
    source: int
    chunk: str | None


def reconstruct(streams, *, kernel: str = "?", device_kind: str | None = None,
                itemsize: int = 2) -> Timeline:
    """Replay per-rank flight streams onto one model clock (see module
    docstring).  ``streams``: list indexed by rank; ``itemsize`` converts
    recorded element counts to bytes (record-mode refs are untyped)."""
    from ..tools import perf_model

    spec = perf_model.chip_spec(device_kind)
    mxu = spec.bf16_tflops * 1e6     # flops per us
    hbm = spec.hbm_gbps * 1e3        # bytes per us
    ici = spec.ici_gbps * 1e3        # bytes per us

    n = len(streams)
    evs = [[e for e in s if e.kind not in ("step", "collective")]
           for s in streams]
    clocks = [0.0] * n
    pcs = [0] * n
    nbar = [0] * n
    wire_bytes = [0] * n
    rows = [RankRow(r) for r in range(n)]
    credits: dict[tuple[int, str], deque] = {}
    waits: list[WaitAttribution] = []
    intervals: list[Interval] = []
    flows: list[tuple[Interval, float, int]] = []
    wire_by_credit: dict[tuple[int, str, int], Interval] = {}
    consumed_seq: dict[tuple[int, str], int] = {}
    issued_seq: dict[tuple[int, str], int] = {}

    def add_credit(rank, sem, amount, ready, source, chunk,
                   wire: Interval | None = None):
        key = (rank, sem)
        credits.setdefault(key, deque()).append(
            _Credit(amount, ready, source, chunk))
        if wire is not None:
            wire_by_credit[(rank, sem, issued_seq.get(key, 0))] = wire
        issued_seq[key] = issued_seq.get(key, 0) + 1

    def available(rank, sem) -> int:
        return sum(c.amount for c in credits.get((rank, sem), ()))

    def wait_step(r, ev) -> bool:
        sem = ev.sem or "?"
        need = max(int(ev.elems), 1)
        if available(r, sem) < need:
            return False
        q = credits[(r, sem)]
        t0 = clocks[r]
        latest = t0
        src = chunk = None
        crit_seq = None
        while need > 0:
            c = q[0]
            take = min(need, c.amount)
            c.amount -= take
            need -= take
            if c.ready >= latest:
                latest = max(latest, c.ready)
                src, chunk = c.source, c.chunk
                crit_seq = consumed_seq.get((r, sem), 0)
            if c.amount == 0:
                q.popleft()
                consumed_seq[(r, sem)] = consumed_seq.get((r, sem), 0) + 1
        t1 = max(t0, latest) + EPS_US
        exposed = max(0.0, latest - t0)
        rows[r].exposed_us += exposed
        intervals.append(Interval(r, "protocol", ev.kind, sem, t0, t1))
        if exposed > 0:
            waits.append(WaitAttribution(
                r, ev.kind, sem, chunk if chunk else ev.chunk, src,
                exposed, t1))
            wire = wire_by_credit.get((r, sem, crit_seq)) \
                if crit_seq is not None else None
            if wire is not None:
                flows.append((wire, t1, r))
        clocks[r] = t1
        pcs[r] += 1
        return True

    def barrier_step(r, ev) -> bool:
        k = nbar[r]
        parked = []
        for p in range(n):
            if nbar[p] != k:
                return False
            if pcs[p] >= len(evs[p]) or evs[p][pcs[p]].kind != "barrier":
                return False
            parked.append(p)
        t_join = max(clocks[p] for p in parked) + EPS_US
        for p in parked:
            rows[p].barrier_us += max(0.0, t_join - EPS_US - clocks[p])
            intervals.append(Interval(p, "protocol", "barrier",
                                      ev.sem or "barrier", clocks[p], t_join))
            clocks[p] = t_join
            pcs[p] += 1
            nbar[p] += 1
        return True

    def step(r) -> bool:
        if pcs[r] >= len(evs[r]):
            return False
        ev = evs[r][pcs[r]]
        t0 = clocks[r]
        if ev.kind in ("wait", "wait_recv", "wait_send"):
            return wait_step(r, ev)
        if ev.kind == "barrier":
            return barrier_step(r, ev)
        if ev.kind == "notify":
            target = ev.peer if ev.peer is not None else r
            hop = 0.0 if target == r else HOP_US
            add_credit(target, ev.sem or "?", max(int(ev.elems), 1),
                       t0 + EPS_US + hop, r, ev.chunk)
            clocks[r] = t0 + EPS_US
        elif ev.kind == "remote_copy":
            nbytes = ev.elems * itemsize
            wire_t = HOP_US + nbytes / ici
            target = ev.peer if ev.peer is not None else r
            wire = Interval(r, "wire", "remote_copy",
                            f"{ev.chunk or '?'} -> rank {target}",
                            t0, t0 + wire_t)
            intervals.append(wire)
            rows[r].wire_us += wire_t
            wire_bytes[r] += nbytes
            if ev.sem2:
                add_credit(r, ev.sem2, ev.elems, t0 + wire_t, r, ev.chunk)
            add_credit(target, ev.sem or "?", ev.elems, t0 + wire_t, r,
                       ev.chunk, wire=wire)
            clocks[r] = t0 + EPS_US
        elif ev.kind == "local_copy":
            nbytes = ev.elems * itemsize
            add_credit(r, ev.sem or "?", ev.elems,
                       t0 + LAUNCH_US + nbytes / hbm, r, ev.chunk)
            clocks[r] = t0 + EPS_US
        elif ev.kind == "compute":
            dur = LAUNCH_US + max(ev.flops / mxu, ev.bytes * itemsize / hbm)
            intervals.append(Interval(r, "protocol", "compute",
                                      ev.op or "compute", t0, t0 + dur))
            rows[r].compute_us += dur
            clocks[r] = t0 + dur
        else:
            clocks[r] = t0 + EPS_US
        pcs[r] += 1
        return True

    progress = True
    while progress:
        progress = False
        for r in range(n):
            while step(r):
                progress = True

    pending = []
    for r in range(n):
        rows[r].finish_us = clocks[r]
        if pcs[r] < len(evs[r]):
            ev = evs[r][pcs[r]]
            pending.append(
                f"rank {r} unreplayable at event #{pcs[r]} "
                f"({ev.kind} {ev.sem or ''}: need {ev.elems}, "
                f"have {available(r, ev.sem or '?')}) — truncated or "
                f"stalled stream")
    finishes = [rw.finish_us for rw in rows] or [0.0]
    critical = max(finishes)
    # SOL lower bound per rank: compute roofline vs wire roofline.  The
    # wire bound serializes BYTES per link but pipelines hop latency
    # (one hop, not one per transfer) — the per-transfer hops in the
    # replay model protocol latency, which overlapped transfers hide.
    sol = max(
        (max(rw.compute_us,
             wire_bytes[rw.rank] / ici + (HOP_US if wire_bytes[rw.rank]
                                          else 0.0))
         for rw in rows),
        default=0.0,
    )
    waits.sort(key=lambda w: -w.exposed_us)
    return Timeline(kernel, n, rows, waits, intervals, flows,
                    critical_us=critical,
                    skew_us=max(finishes) - min(finishes),
                    sol_us=sol, stalled=bool(pending),
                    pending=tuple(pending))


# ---------------------------------------------------------------------------
# wall-clock alignment (for streams carrying real per-process timestamps)


def align_clocks(streams) -> list[float]:
    """Per-rank offsets (us, add to each rank's ``t_us``) that bring the
    hub-barrier events into coincidence with rank 0's — the cross-process
    clock alignment step for wall-timestamped streams (each process's
    monotonic clock has an arbitrary epoch).  Uses the mean offset over
    the barrier ordinals every rank recorded; ranks with no common
    barrier get offset 0."""
    bars = [[e.t_us for e in s if e.kind == "barrier"] for s in streams]
    k = min((len(b) for b in bars), default=0)
    if k == 0:
        return [0.0] * len(streams)
    offs = []
    for b in bars:
        offs.append(sum(bars[0][i] - b[i] for i in range(k)) / k)
    return offs


def apply_offsets(streams, offsets):
    """Shifted copies of ``streams`` (event objects are replaced, inputs
    untouched)."""
    import copy

    out = []
    for s, off in zip(streams, offsets):
        shifted = []
        for e in s:
            e2 = copy.copy(e)
            e2.t_us = e.t_us + off
            shifted.append(e2)
        out.append(shifted)
    return out


# ---------------------------------------------------------------------------
# rendering


def format_table(timelines) -> str:
    """The per-collective table: one block per kernel with per-rank
    compute / wire / exposed-wait / straggler-skew columns, the summary
    line (critical path, pct of SOL), and the wait-attribution list."""
    if isinstance(timelines, Timeline):
        timelines = [timelines]
    lines = []
    header = ("kernel", "rank", "compute_us", "wire_us", "exposed_us",
              "barrier_us", "finish_us")
    for tl in timelines:
        table = [header]
        for rw in tl.rows:
            table.append((tl.kernel, str(rw.rank), f"{rw.compute_us:.3f}",
                          f"{rw.wire_us:.3f}", f"{rw.exposed_us:.3f}",
                          f"{rw.barrier_us:.3f}", f"{rw.finish_us:.3f}"))
        widths = [max(len(row[i]) for row in table)
                  for i in range(len(header))]
        for i, row in enumerate(table):
            lines.append("  ".join(
                c.ljust(w) if j == 0 else c.rjust(w)
                for j, (c, w) in enumerate(zip(row, widths))))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        lines.append(
            f"{tl.kernel}: ranks={tl.n} critical={tl.critical_us:.3f}us "
            f"skew={tl.skew_us:.3f}us sol={tl.sol_us:.3f}us "
            f"pct_sol={100 * tl.pct_sol:.1f}%"
        )
        if tl.waits:
            lines.append("wait attribution (semaphore, chunk, peer):")
            for w in tl.waits[:16]:
                lines.append(f"  {w.describe()}")
        if tl.stalled:
            lines.append("PARTIAL RECONSTRUCTION:")
            for p in tl.pending:
                lines.append(f"  {p}")
        lines.append("")
    return "\n".join(lines)


def to_chrome(tl: Timeline) -> list[dict]:
    """Chrome-trace events of a reconstructed timeline: per-rank protocol
    and wire lanes plus FLOW events linking each attributed wait to the
    transfer it starved for (the arrows the reference's merged profiler
    view shows between producer and consumer kernels)."""
    evs = []
    lanes = {"protocol": 0, "wire": 1}
    for iv in tl.intervals:
        evs.append({
            "name": iv.label, "cat": iv.kind, "ph": "X",
            "ts": iv.t0_us, "dur": max(iv.t1_us - iv.t0_us, EPS_US),
            "pid": iv.rank, "tid": lanes[iv.lane],
        })
    for i, (wire, t_end, dst) in enumerate(tl.flows):
        common = {"cat": "stall", "name": "starved-for", "id": i + 1}
        evs.append({**common, "ph": "s", "ts": wire.t1_us,
                    "pid": wire.rank, "tid": lanes["wire"]})
        evs.append({**common, "ph": "f", "bp": "e", "ts": t_end,
                    "pid": dst, "tid": lanes["protocol"]})
    return evs


def check_balanced(tl: Timeline, *, tol: float = 1e-6) -> list[str]:
    """Symmetry checks for a ring kernel's reconstruction (the
    ``tdt_lint --timeline`` smoke): every rank of a symmetric ring must
    reconstruct identical exposed-wait totals, every recv attribution
    must name its (semaphore, chunk, peer) triple, and the replay must
    complete.  Returns human-readable problems (empty = balanced)."""
    problems = []
    if tl.stalled:
        problems.extend(f"stalled: {p}" for p in tl.pending)
    exposed = [rw.exposed_us for rw in tl.rows]
    if exposed and max(exposed) - min(exposed) > tol:
        problems.append(
            f"exposed-wait imbalance across ranks: {exposed} "
            f"(symmetric ring must reconstruct symmetrically)")
    for w in tl.waits:
        if w.kind == "wait_recv" and (w.sem is None or w.chunk is None
                                      or w.source is None):
            problems.append(
                f"unattributed recv stall: {w.describe()} — the flight "
                f"stream lost the (sem, chunk, peer) identity")
    return problems
