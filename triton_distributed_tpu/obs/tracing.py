"""Span-based wall-time tracing with Chrome-trace export.

``span("decode_step", cat="step")`` context managers record host
wall-time intervals into a process-local bounded buffer; ``export``
writes the buffer as Chrome-trace JSON that ``tools.trace_merge`` can
merge across hosts (each process exports its own file; the merger
offsets pids so the lanes stay disjoint in one timeline).

Categories are the contract the overlap report (``obs.report``) reads:

- ``step``     one serving iteration (``decode_step``, ``prefill``)
- ``comm``     a collective's host-side interval (eager calls only — a
               collective traced into a jit program records once, at
               trace time, and is skipped; see ``obs.record_collective``)
- ``compute``  a compute interval inside a step
- anything else is carried through for the timeline but ignored by the
  overlap arithmetic.

Timebase: ``ts`` is ``time.time_ns() // 1000`` (wall clock, us — so
per-host traces land in roughly the same epoch when merged) and ``dur``
is measured with ``perf_counter_ns`` (monotonic).  Cross-host clock skew
shifts lanes relative to each other but never distorts the per-step
overlap ratios, which are computed within one pid.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque

# Bounded: ~180 bytes/event; 200k events ~= 36 MB worst case.  Oldest
# events drop first — a long serve loop keeps its most recent window.
MAX_EVENTS = 200_000

_lock = threading.Lock()
_events: deque = deque(maxlen=MAX_EVENTS)
_tids: dict[int, int] = {}
_pid_cache: list = []


def _pid() -> int:
    """JAX process index when a backend exists, else 0 — lazy so that
    importing ``obs`` (e.g. from ``scripts/obs_report.py --selftest``)
    never initializes a device backend."""
    if not _pid_cache:
        try:
            import jax

            _pid_cache.append(int(jax.process_index()))
        except Exception:
            _pid_cache.append(0)
    return _pid_cache[0]


def _tid() -> int:
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        with _lock:
            t = _tids.setdefault(ident, len(_tids))
    return t


class _Span:
    __slots__ = ("name", "cat", "args", "_t0_wall", "_t0_mono")

    def __init__(self, name: str, cat: str, args: dict):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0_wall = time.time_ns()
        self._t0_mono = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_us = (time.perf_counter_ns() - self._t0_mono) / 1e3
        ev = {
            "name": self.name, "cat": self.cat, "ph": "X",
            "ts": self._t0_wall // 1000, "dur": dur_us,
            "pid": _pid(), "tid": _tid(),
        }
        if self.args:
            ev["args"] = self.args
        _events.append(ev)
        return False


_NULL = contextlib.nullcontext()

_pkg_cache: list = []


def _enabled() -> bool:
    # read the package's cached flag through a memoized module ref: the
    # disabled fast path costs one attribute load, not an import lookup
    # per call (spans sit on the serve loop's per-token path); the
    # thread-local suppression check only runs once recording is on
    if not _pkg_cache:
        import sys

        _pkg_cache.append(sys.modules[__package__])
    pkg = _pkg_cache[0]
    return pkg._ENABLED and not pkg._suppressed()


def span(name: str, cat: str = "compute", /, **args):
    """Record a wall-time interval for the enclosed block.  A no-op
    (shared null context, zero allocation) when observability is off."""
    if not _enabled():
        return _NULL
    return _Span(name, cat, args)


def instant(name: str, cat: str = "mark", /, **args) -> None:
    """Record a zero-duration instant event (``ph: i``)."""
    if not _enabled():
        return
    ev = {"name": name, "cat": cat, "ph": "i", "s": "p",
          "ts": time.time_ns() // 1000, "pid": _pid(), "tid": _tid()}
    if args:
        ev["args"] = args
    _events.append(ev)


def events() -> list[dict]:
    """Copy of the recorded events (oldest first)."""
    return list(_events)


def clear() -> None:
    _events.clear()


def export(path: str, *, clear_buffer: bool = False) -> str:
    """Write the buffered spans as Chrome-trace JSON.

    The envelope is compact with ``traceEvents`` LAST — the exact layout
    under which ``tools.trace_merge``'s native and Python paths produce
    byte-identical merges — so per-process exports from a multi-host run
    merge into one timeline with ``merge_traces([...], ranks=[...])``.
    """
    evs = list(_events)
    if clear_buffer:
        _events.clear()
    with open(path, "w") as f:
        f.write('{"displayTimeUnit":"ms","traceEvents":')
        f.write(json.dumps(evs, separators=(",", ":")))
        f.write("}")
    return path
