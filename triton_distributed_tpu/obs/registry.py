"""Process-local metrics registry: counters, gauges, histograms.

Reference: the reference framework exposes its runtime state only through
ad-hoc prints and the torch profiler; T3 (arxiv 2401.16677) argues the
compute/collective interleave must be *observable* before it is tunable.
This registry is the zero-dependency substrate every instrumented call
site writes into: thread-safe, allocation-light, and snapshot-exportable
(``obs.export``) without stopping the world.

Design constraints:

- **Zero deps**: stdlib only — the serving container must not grow a
  prometheus_client/opentelemetry wheel for this.
- **Thread-safe**: one lock per registry guards the metric map; each
  metric guards its own mutation (collectives and the engine can be
  driven from multiple host threads).
- **Fixed histogram buckets**: cumulative bucket counts with boundaries
  frozen at creation, so the Prometheus text exposition is exact (no
  client-side rebinning) and two processes' histograms merge by adding
  counts.
- **Labels**: small, closed sets only (op name, method name).  Label
  values become part of the metric identity; unbounded label values
  (shapes, request ids) belong in spans (``obs.tracing``), not here.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable

# Latency buckets in milliseconds: 50 us .. 10 s, the span from one
# sub-millisecond collective chunk to a cold-compile prefill.
DEFAULT_LATENCY_BUCKETS_MS: tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# Byte-size buckets: 1 KiB .. 1 GiB in powers of 4 — collective payloads.
DEFAULT_BYTES_BUCKETS: tuple[float, ...] = tuple(
    float(1 << s) for s in range(10, 31, 2)
)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def bucket_quantile(buckets, counts, count: int, maximum, q: float):
    """Quantile estimate from cumulative bucket counts: the bound of the
    first bucket whose count covers ``q``, the observed ``maximum`` for
    quantiles landing in the +Inf bucket, ``None`` when empty.  Shared by
    :meth:`Histogram.quantile` and the exporters' summary table."""
    if not count:
        return None
    target = q * count
    for b, c in zip(buckets, counts):
        if c >= target:
            return b
    return maximum


class Counter:
    """Monotonically increasing float counter."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def row(self) -> dict:
        return {"kind": "counter", "name": self.name, "labels": self.labels,
                "value": self._value}


class Gauge:
    """Last-write-wins float gauge."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        return self._value

    def row(self) -> dict:
        return {"kind": "gauge", "name": self.name, "labels": self.labels,
                "value": self._value}


class Histogram:
    """Fixed-boundary histogram with cumulative Prometheus semantics.

    ``counts[i]`` counts observations ``<= buckets[i]``; the implicit
    final bucket (``+Inf``) is ``count``.  Boundaries are frozen at
    creation so exported bucket counts from different processes/rounds
    are directly addable.
    """

    __slots__ = ("name", "labels", "buckets", "_lock", "_counts",
                 "_sum", "_count", "_min", "_max")

    def __init__(self, name: str, labels: dict,
                 buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS_MS):
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(bs):
            raise ValueError(f"histogram {name}: buckets must be sorted "
                             f"and non-empty, got {bs}")
        self.name = name
        self.labels = dict(labels)
        self.buckets = bs
        self._lock = threading.Lock()
        self._counts = [0] * len(bs)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            # cumulative: bump every bucket whose bound admits v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """Bucket-boundary quantile estimate (see :func:`bucket_quantile`);
        0.0 when no observations."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            est = bucket_quantile(self.buckets, self._counts, self._count,
                                  self._max, q)
            return 0.0 if est is None else est

    def row(self) -> dict:
        with self._lock:
            return {
                "kind": "histogram", "name": self.name,
                "labels": self.labels, "buckets": list(self.buckets),
                "counts": list(self._counts), "sum": self._sum,
                "count": self._count,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
            }


class Registry:
    """Named metric map; ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent at a call site in a hot loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, object] = {}

    def _get(self, kind: str, cls, name: str, labels: dict, *args):
        key = (kind, name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, labels, *args)
                    self._metrics[key] = m
        return m

    def counter(self, name: str, /, **labels) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, /, **labels) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS_MS,
                  /, **labels) -> Histogram:
        return self._get("histogram", Histogram, name, labels, buckets)

    def snapshot(self) -> list[dict]:
        """Point-in-time rows for the exporters, sorted by (name, labels)
        so diffs and round trips are stable."""
        with self._lock:
            metrics = list(self._metrics.items())
        rows = [m.row() for _, m in metrics]
        rows.sort(key=lambda r: (r["name"], _label_key(r["labels"])))
        return rows

    def reset(self) -> None:
        """Drop every metric (tests and per-capture benches)."""
        with self._lock:
            self._metrics.clear()


# The process-global registry every instrumented call site writes into.
REGISTRY = Registry()
