"""Cross-replica telemetry federation + fleet-scope anomaly detection
(``TDT_FLEET_OBS=1``).

The fleet tier (``serve.fleet.FleetRouter``) runs N scheduler replicas,
but until ISSUE 19 they all fed ONE process-global ``ServeStats`` — a
regressed fleet p99 could not name the replica that caused it.  This
module federates the telemetry:

- :class:`ReplicaStats` — a per-replica ``ServeStats`` whose sketches
  and rate windows TEE every observation into the union collector
  (``obs.serve_stats.STATS`` by default).  The scheduler's feed sites
  write ``self.stats`` (``Scheduler.stats``), so installing a
  ``ReplicaStats`` per replica buys drill-down without touching the
  serve loop — and the union keeps seeing the exact stream it always
  saw, which is what pins the federation: **merging the per-replica
  sketches reproduces the union sketch bucket-for-bucket**, so the
  fleet-merged p99 equals observing the union stream directly (within
  the sketch's alpha; ``tests/test_fleet_obs.py`` pins equality).
- :class:`FleetStats` — the fleet view: merged ttft/request sketches
  with per-replica drill-down, summed token/request rates, imbalance
  gauges (pool-occupancy spread across same-role replicas,
  routing-concentration fraction over the ledger's admission
  decisions), and a same-role SKEW detector (p99 ratio across replicas
  playing the same role).
- Fleet-scope anomaly detection: every ``FLEET_WINDOW_STEPS`` fleet
  steps the window's totals are judged against ``obs.history.Band``
  bands (the ONE band implementation), and a breach emits a
  :class:`FleetAnomalyEvent` carrying the **decision-ledger entries
  from its window** (``obs.decisions``) — "fleet p99 breached, and
  here are the rebalance + quarantine decisions inside it".
- Export: ``/debug/fleet`` (``obs.server``), ``tdt_fleet_*`` series on
  ``/metrics`` (:func:`to_prometheus`), and a Chrome fleet timeline —
  one lane per replica with quarantine/lost/role-change spans
  synthesized from the ledger, merged with the request-trace chains
  via ``tools.trace_merge`` ``ts_offsets`` (:func:`export_fleet_timeline`).

The TDT_OBS discipline holds: with ``TDT_FLEET_OBS`` unset the router
never installs the plane and the fleet replay is byte-identical.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from collections import deque

from . import decisions, history
from . import serve_stats as serve_stats_mod
from .serve_stats import QuantileSketch, ServeStats, WindowedRate

# fleet steps per anomaly window (matches the continuous profiler's
# default cadence; override per FleetStats)
FLEET_WINDOW_STEPS = 64
MAX_RETAINED = 32
SERVE_QUANTILES = serve_stats_mod.SERVE_QUANTILES

# the sketch / rate attributes ReplicaStats tees (every ServeStats
# sketch and window the scheduler plane feeds)
SKETCH_NAMES = ("request_ms", "prefill_ms", "decode_ms_per_token",
                "ttft_ms", "handoff_ms")
RATE_NAMES = ("tokens", "requests", "failed_requests", "sheds",
              "preemptions", "evicted_pages", "handoff_pages")

# the admission-plane decision kinds routing concentration counts over
ROUTE_KINDS = ("route", "affinity_hit", "affinity_redirect")


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_FLEET_OBS")


# Cached bool, the TDT_OBS discipline: a disabled FleetRouter pays one
# check at construction and nothing per step.
_ENABLED = _env_enabled()

_LOCK = threading.Lock()
_FLEET: "FleetStats | None" = None


def enabled() -> bool:
    """Whether the federation plane arms (``TDT_FLEET_OBS=1`` or
    :func:`enable`)."""
    return _ENABLED


def enable(on: bool | None = True) -> bool:
    """Turn the plane on/off; ``None`` re-reads ``TDT_FLEET_OBS``."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


def window_steps() -> int:
    """Fleet anomaly window length (``TDT_FLEET_WINDOW``, default 64
    fleet steps)."""
    try:
        return max(1, int(os.environ.get("TDT_FLEET_WINDOW", "")
                          or FLEET_WINDOW_STEPS))
    except ValueError:
        return FLEET_WINDOW_STEPS


# ---------------------------------------------------------------------------
# the tee: per-replica sketches that keep the union stream whole


class _TeeSketch(QuantileSketch):
    """A sketch that forwards every observation into a union sketch of
    the SAME gamma.  The per-replica copy and the union therefore hold
    the same log-bucket keys for the same values — merging the replica
    copies reconstructs the union bucket-for-bucket (the federation
    pin)."""

    __slots__ = ("_union",)

    def __init__(self, union: QuantileSketch):
        super().__init__(alpha=union.alpha, max_buckets=union.max_buckets)
        self._union = union

    def observe(self, v: float, exemplar: str | None = None) -> None:
        super().observe(v, exemplar)
        self._union.observe(v, exemplar)


class _TeeRate(WindowedRate):
    """A rate window teeing into a union window — the SAME ``now`` is
    used for both adds, so the per-second buckets stay aligned and the
    union total equals the sum of the replica totals."""

    __slots__ = ("_union",)

    def __init__(self, union: WindowedRate):
        super().__init__(window_s=union.window_s)
        self._union = union

    def add(self, v: float = 1.0, now: float | None = None) -> None:
        import time

        now = time.monotonic() if now is None else now
        super().add(v, now=now)
        self._union.add(v, now=now)


class ReplicaStats(ServeStats):
    """One replica's ``ServeStats`` with every sketch/rate teeing into
    the union collector.  Installed as ``Scheduler.stats`` by
    :func:`attach`; gauges and queue depth stay replica-local (the
    router already publishes them under ``replica_<id>_*`` names)."""

    def __init__(self, replica_id: str, union: ServeStats):
        super().__init__(alpha=union._alpha, window_s=union._window_s)
        self.replica_id = str(replica_id)
        self.union = union
        for name in SKETCH_NAMES:
            setattr(self, name, _TeeSketch(getattr(union, name)))
        for name in RATE_NAMES:
            setattr(self, name, _TeeRate(getattr(union, name)))

    def reset(self) -> None:
        self.__init__(self.replica_id, self.union)


# ---------------------------------------------------------------------------
# fleet anomaly events


@dataclasses.dataclass(frozen=True)
class FleetAnomalyEvent:
    """One fleet-window band breach, carrying the ledger entries from
    its window — the explanation loop the module docstring promises."""

    metric: str
    value: float
    band: tuple[float, float]
    direction: str
    drift_pct: float
    window: int
    step_start: int
    step_end: int
    exemplar: str | None               # p99 exemplar trace id, if traced
    decisions: tuple[dict, ...]        # ledger records inside the window
    # best-vs-worst replica attribution (obs.diff.diff_replicas over
    # the breached metric's sketch) — the "why", when >= 2 replicas
    # carried samples at detection time
    diff: dict | None = None

    def summary(self) -> str:
        s = (f"fleet {self.metric}={self.value:g} outside healthy band "
             f"[{self.band[0]:g}, {self.band[1]:g}] "
             f"({100 * self.drift_pct:.1f}% worse, window "
             f"#{self.window} steps {self.step_start}..{self.step_end})")
        if self.decisions:
            kinds: dict[str, int] = {}
            for d in self.decisions:
                k = d.get("kind", "?")
                kinds[k] = kinds.get(k, 0) + 1
            s += ("; " + str(len(self.decisions)) + " ledger decisions ("
                  + ", ".join(f"{k} x{n}" for k, n in sorted(kinds.items()))
                  + ")")
        if self.exemplar:
            s += f"; p99 exemplar {self.exemplar}"
        if self.diff and self.diff.get("terms"):
            s += f"; diff: {self.diff['summary']}"
        return s

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["summary"] = self.summary()
        return d


# ---------------------------------------------------------------------------
# the federation plane


class FleetStats:
    """Federated fleet telemetry over per-replica :class:`ReplicaStats`
    (see module docstring).  ``bands`` is a metric->``history.Band``
    map for the window comparator (the harness/lint injection point —
    empty by default, so an unconfigured plane never warns);
    ``record=False`` keeps a harness run out of the process warning
    state."""

    def __init__(self, *, union: ServeStats | None = None,
                 window_steps: int | None = None,
                 bands: dict[str, history.Band] | None = None,
                 record: bool = True):
        self.union = union if union is not None else serve_stats_mod.STATS
        self.window_steps = int(window_steps) if window_steps \
            else globals()["window_steps"]()
        self.bands = dict(bands) if bands else {}
        self.record = record
        self._lock = threading.Lock()
        self.replicas: dict[str, ReplicaStats] = {}
        self.roles: dict[str, str] = {}
        self.windows = 0
        # first fleet step of the open window: 0, not 1 — admission
        # decisions recorded before the first step carry step=0
        self._win_start = 0
        self.last_totals: dict = {}
        self._events: deque = deque(maxlen=MAX_RETAINED)
        self._current: tuple = ()
        self.anomalies_total = 0

    # -- replica registry --------------------------------------------------

    def replica(self, replica_id: str, role: str) -> ReplicaStats:
        """Get-or-create the replica's tee collector (idempotent; the
        role is refreshed — conversions call :meth:`set_role`)."""
        with self._lock:
            rs = self.replicas.get(replica_id)
            if rs is None:
                rs = self.replicas[replica_id] = ReplicaStats(
                    replica_id, self.union)
            self.roles[replica_id] = role
        return rs

    def set_role(self, replica_id: str, role: str) -> None:
        with self._lock:
            self.roles[replica_id] = role

    # -- federation reads --------------------------------------------------

    def merged(self, name: str) -> QuantileSketch:
        """A fresh sketch holding the merge of every replica's ``name``
        sketch — the federation read.  Merge-safe by construction (same
        gamma everywhere; ``QuantileSketch.merge`` adds buckets,
        exemplars ride along)."""
        with self._lock:
            reps = list(self.replicas.values())
        out = QuantileSketch(alpha=self.union._alpha)
        for rs in reps:
            out.merge(getattr(rs, name))
        return out

    def merged_rate(self, name: str) -> float:
        with self._lock:
            reps = list(self.replicas.values())
        return sum(getattr(rs, name).rate() for rs in reps)

    def _role_groups(self) -> dict[str, list[ReplicaStats]]:
        with self._lock:
            return {
                role: [self.replicas[rid]
                       for rid, r in self.roles.items() if r == role
                       and rid in self.replicas]
                for role in sorted(set(self.roles.values()))
            }

    def role_skew(self) -> float:
        """The same-role skew detector: per role, the p99 of the
        role-appropriate sketch (``ttft_ms`` for prefill — first tokens
        land there; ``request_ms`` for decode — completions land there)
        across that role's replicas, reported as ``max/min - 1`` (0.0 =
        perfectly balanced).  The fleet number is the worst role."""
        worst = 0.0
        for role, reps in self._role_groups().items():
            name = "ttft_ms" if role == "prefill" else "request_ms"
            p99s = [getattr(rs, name).quantile(0.99) for rs in reps
                    if getattr(rs, name).count > 0]
            if len(p99s) < 2 or min(p99s) <= 0.0:
                continue
            worst = max(worst, max(p99s) / min(p99s) - 1.0)
        return worst

    def imbalance(self, router=None) -> dict[str, float]:
        """The imbalance gauges: ``occupancy_spread`` (max-min pool
        occupancy among same-role ADMITTING replicas, worst role) needs
        the live router; ``routing_concentration`` (fraction of the
        window's admission decisions landing on the most-picked
        replica) reads the ledger."""
        spread = 0.0
        if router is not None:
            by_role: dict[str, list[float]] = {}
            for rep in router.replicas:
                if router._admitting(rep):
                    by_role.setdefault(rep.role, []).append(
                        rep.scheduler.pool.occupancy())
            for occ in by_role.values():
                if len(occ) >= 2:
                    spread = max(spread, max(occ) - min(occ))
        routes: dict[str, int] = {}
        for rec in decisions.query(step_range=(self._win_start, 1 << 62)):
            if rec.kind in ROUTE_KINDS and rec.replica is not None:
                routes[rec.replica] = routes.get(rec.replica, 0) + 1
        total = sum(routes.values())
        conc = max(routes.values()) / total if total else 0.0
        return {"fleet_occupancy_spread": spread,
                "fleet_routing_concentration": conc}

    # -- the window loop ---------------------------------------------------

    def on_step(self, step: int, router=None) -> list[FleetAnomalyEvent]:
        """The router's per-step hook: rotate a window (and run the
        band comparator) every ``window_steps`` fleet steps.  Returns
        the new window's breaches (empty off-boundary)."""
        if step % self.window_steps != 0:
            return []
        return self._rotate(step, router)

    def _rotate(self, step: int, router=None) -> list[FleetAnomalyEvent]:
        win = (self._win_start, step)
        recs = decisions.query(step_range=win)
        totals = {
            "fleet_ttft_ms_p99": self.merged("ttft_ms").quantile(0.99),
            "fleet_request_ms_p99":
                self.merged("request_ms").quantile(0.99),
            "fleet_tokens_per_s": self.merged_rate("tokens"),
            "fleet_requests_per_s": self.merged_rate("requests"),
            "fleet_decision_rate": len(recs) / float(self.window_steps),
            "fleet_role_skew": self.role_skew(),
        }
        totals.update(self.imbalance(router))
        events: list[FleetAnomalyEvent] = []
        for metric, band in self.bands.items():
            value = totals.get(metric)
            if not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            drift = band.breach(float(value))
            if drift is None:
                continue
            exemplar = None
            for name in ("request_ms", "ttft_ms"):
                exemplar = getattr(self.union, name).exemplar(0.99)
                if exemplar:
                    break
            # best-vs-worst replica attribution on the breached
            # metric's sketch (fallback: whole-request latency) — the
            # two-fleet-replicas pairing of obs.diff
            attribution = None
            try:
                from . import diff as diff_mod

                sketch = {"fleet_ttft_ms_p99": "ttft_ms",
                          "fleet_request_ms_p99": "request_ms"}.get(
                              metric, "request_ms")
                with self._lock:
                    reps = [r for r in self.replicas.values()
                            if getattr(r, sketch).count > 0]
                if len(reps) >= 2:
                    reps.sort(
                        key=lambda r: getattr(r, sketch).quantile(0.99))
                    attribution = diff_mod.diff_replicas(
                        reps[0], reps[-1])
            except Exception:
                attribution = None
            events.append(FleetAnomalyEvent(
                metric=metric, value=float(value),
                band=(band.lo, band.hi), direction=band.direction,
                drift_pct=drift, window=self.windows,
                step_start=win[0], step_end=win[1],
                exemplar=exemplar,
                decisions=tuple(r.to_dict() for r in recs),
                diff=attribution,
            ))
        with self._lock:
            self.windows += 1
            self.last_totals = dict(totals)
            self._win_start = step + 1
            if self.record:
                self._current = tuple(events)
                for e in events:
                    self._events.append(e)
                    self.anomalies_total += 1
        return events

    # -- read side ---------------------------------------------------------

    def current(self) -> list[FleetAnomalyEvent]:
        """The latest completed window's breaches (the warning
        state)."""
        return list(self._current)

    def recent_events(self, n: int = 8) -> list[FleetAnomalyEvent]:
        with self._lock:
            return list(self._events)[-int(n):]

    def health_fragment(self) -> dict | None:
        """Attached under ``fleet_obs`` by ``FleetRouter.health()`` when
        the latest window breached: a WARNING state, never a status
        flip (``/healthz`` stays 200 — drift never 503s, the PR-15
        rule).  None when healthy, so an unarmed snapshot is
        byte-identical."""
        cur = self.current()
        if not cur:
            return None
        return {
            "status": "warn",
            "anomalies": [e.summary() for e in cur],
            "total": self.anomalies_total,
        }

    def snapshot(self) -> dict:
        """The ``/debug/fleet`` stats block: merged views, per-replica
        drill-down, the last window's imbalance gauges, retained
        anomalies."""
        with self._lock:
            reps = dict(self.replicas)
            roles = dict(self.roles)
            totals = dict(self.last_totals)
            windows = self.windows
            cur = list(self._current)
            recent = list(self._events)[-8:]
            total = self.anomalies_total
        merged_ttft = self.merged("ttft_ms")
        merged_req = self.merged("request_ms")
        return {
            "window_steps": self.window_steps,
            "windows": windows,
            "merged": {
                "ttft_ms": merged_ttft.to_dict(),
                "request_ms": merged_req.to_dict(),
                "tokens_per_s_window": self.merged_rate("tokens"),
                "requests_per_s_window": self.merged_rate("requests"),
                "requests_total": sum(rs.requests.total
                                      for rs in reps.values()),
            },
            "replicas": {
                rid: {
                    "role": roles.get(rid),
                    "ttft_ms_p99": rs.ttft_ms.quantile(0.99),
                    "request_ms_p99": rs.request_ms.quantile(0.99),
                    "tokens_per_s_window": rs.tokens.rate(),
                    "tokens_total": rs.tokens.total,
                    "requests_total": rs.requests.total,
                    "sheds_total": rs.sheds.total,
                    "preemptions_total": rs.preemptions.total,
                }
                for rid, rs in sorted(reps.items())
            },
            "last_window_totals": totals,
            "anomalies": [e.to_dict() for e in cur],
            "recent_anomalies": [e.summary() for e in recent],
            "anomalies_total": total,
        }

    def to_prometheus(self) -> str:
        """The ``tdt_fleet_*`` series ``obs.server.metrics_text``
        appends: merged sketch summaries, fleet gauges, per-replica
        labelled drill-down gauges.  Empty with no replicas installed
        (the plane never pollutes a non-fleet scrape)."""
        with self._lock:
            reps = dict(self.replicas)
            roles = dict(self.roles)
            totals = dict(self.last_totals)
        if not reps:
            return ""
        lines: list[str] = []

        def sk(name: str, sketch: QuantileSketch) -> None:
            lines.append(f"# TYPE {name} summary")
            for q in SERVE_QUANTILES:
                lines.append(
                    f'{name}{{quantile="{q:g}"}} {sketch.quantile(q)!r}')
            lines.append(f"{name}_sum {sketch.sum!r}")
            lines.append(f"{name}_count {sketch.count}")

        sk("tdt_fleet_ttft_ms", self.merged("ttft_ms"))
        sk("tdt_fleet_request_ms", self.merged("request_ms"))

        def g(name: str, v: float) -> None:
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(v)!r}")

        g("tdt_fleet_replicas", len(reps))
        g("tdt_fleet_windows", self.windows)
        g("tdt_fleet_tokens_per_s_window", self.merged_rate("tokens"))
        g("tdt_fleet_requests_per_s_window", self.merged_rate("requests"))
        g("tdt_fleet_role_skew", self.role_skew())
        g("tdt_fleet_anomalies_total", self.anomalies_total)
        for name in ("fleet_occupancy_spread",
                     "fleet_routing_concentration",
                     "fleet_decision_rate"):
            if name in totals:
                g("tdt_" + name, totals[name])
        for metric in ("ttft_ms_p99", "request_ms_p99",
                       "tokens_per_s_window", "requests_total"):
            lines.append(f"# TYPE tdt_fleet_replica_{metric} gauge")
            for rid, rs in sorted(reps.items()):
                if metric == "ttft_ms_p99":
                    v = rs.ttft_ms.quantile(0.99)
                elif metric == "request_ms_p99":
                    v = rs.request_ms.quantile(0.99)
                elif metric == "tokens_per_s_window":
                    v = rs.tokens.rate()
                else:
                    v = rs.requests.total
                role = roles.get(rid, "")
                lines.append(
                    f'tdt_fleet_replica_{metric}{{replica="{rid}",'
                    f'role="{role}"}} {float(v)!r}')
        return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# module singleton + the FleetRouter hooks


def current() -> FleetStats | None:
    """The process federation plane, if a router attached one (or a
    harness installed one)."""
    return _FLEET


def install(fs: FleetStats | None) -> FleetStats | None:
    """Install (or clear, with None) the process plane; returns the
    previous one."""
    global _FLEET
    with _LOCK:
        prev, _FLEET = _FLEET, fs
    return prev


def reset() -> None:
    install(None)


def attach(router) -> FleetStats | None:
    """The ``FleetRouter.__init__`` hook: with ``TDT_FLEET_OBS`` armed,
    build a fresh :class:`FleetStats`, install it as the process plane
    (latest router wins, the ``obs.server`` register_engine rule), and
    swap a :class:`ReplicaStats` tee into every replica's scheduler.
    Returns None (and touches nothing) when the plane is off — the
    byte-identical pin."""
    if not _ENABLED:
        return None
    fs = FleetStats()
    install(fs)
    for rep in router.replicas:
        rep.scheduler.stats = fs.replica(rep.replica_id, rep.role)
    return fs


def snapshot_dump() -> dict:
    """The fleet-stats block of ``/debug/fleet`` (stub when the plane
    never armed, so a dashboard can probe for the capability)."""
    fs = _FLEET
    if fs is None:
        return {"enabled": enabled(),
                "hint": "set TDT_FLEET_OBS=1 (docs/observability.md)"}
    out = fs.snapshot()
    out["enabled"] = enabled()
    return out


def health_fragment() -> dict | None:
    fs = _FLEET
    return None if fs is None else fs.health_fragment()


def to_prometheus() -> str:
    fs = _FLEET
    return "" if fs is None else fs.to_prometheus()


# ---------------------------------------------------------------------------
# Chrome fleet timeline


def to_chrome(records, *, replica_order=None) -> list[dict]:
    """Chrome-trace events synthesized from ledger records: one pid
    LANE per replica (ordering stable: ``replica_order`` first, then
    first-seen), quarantine (drain -> readmit/evict-end) and lost spans
    as ``X`` events, conversions/failovers/recruits as instants.  The
    high-volume admission kinds (route/affinity) are omitted — the
    request chains themselves carry that story when merged."""
    recs = [r.to_dict() if hasattr(r, "to_dict") else dict(r)
            for r in records]
    lanes: dict[str, int] = {}
    for rid in (replica_order or ()):
        lanes.setdefault(str(rid), 8000 + len(lanes))
    for d in recs:
        rid = d.get("replica")
        if rid is not None:
            lanes.setdefault(str(rid), 8000 + len(lanes))
    evs: list[dict] = []
    t_max = max((float(d.get("t_us", 0.0)) for d in recs), default=0.0)
    open_spans: dict[tuple[str, str], dict] = {}

    def close(rid: str, name: str, t1: float, end_kind: str) -> None:
        span = open_spans.pop((rid, name), None)
        if span is not None:
            span["dur"] = max(0.0, t1 - span["ts"])
            span["args"]["end"] = end_kind
            evs.append(span)

    for d in recs:
        rid = str(d.get("replica")) if d.get("replica") is not None \
            else None
        if rid is None:
            continue
        kind = d.get("kind")
        t = float(d.get("t_us", 0.0))
        pid = lanes[rid]
        args = {"seq": d.get("seq"), "step": d.get("step"),
                "inputs": d.get("inputs") or {}}
        if kind == "quarantine_drain":
            open_spans.setdefault(
                (rid, "quarantine"),
                {"name": "quarantine", "cat": "fleet", "ph": "X",
                 "ts": t, "dur": 0.0, "pid": pid, "tid": 0,
                 "args": dict(args)})
        elif kind in ("readmit", "quarantine_evict"):
            if kind == "readmit":
                close(rid, "quarantine", t, "readmit")
            evs.append({"name": kind, "cat": "fleet", "ph": "i",
                        "s": "p", "ts": t, "pid": pid, "tid": 0,
                        "args": args})
        elif kind == "replica_lost":
            open_spans[(rid, "lost")] = {
                "name": "lost", "cat": "fleet", "ph": "X", "ts": t,
                "dur": 0.0, "pid": pid, "tid": 0, "args": dict(args)}
        elif kind == "convert":
            close(rid, "recruit", t, "convert")
            evs.append({"name": "convert", "cat": "fleet", "ph": "i",
                        "s": "p", "ts": t, "pid": pid, "tid": 0,
                        "args": args})
        elif kind == "recruit":
            open_spans.setdefault(
                (rid, "recruit"),
                {"name": "recruit", "cat": "fleet", "ph": "X", "ts": t,
                 "dur": 0.0, "pid": pid, "tid": 0, "args": dict(args)})
        elif kind in ("failover", "failover_shed", "reprefill", "shed",
                      "rebalance_streak", "readmit_probe"):
            evs.append({"name": kind, "cat": "fleet", "ph": "i",
                        "s": "p", "ts": t, "pid": pid, "tid": 0,
                        "args": {**args,
                                 "request_id": d.get("request_id")}})
    for (rid, name), span in open_spans.items():
        # still open at export time: extend to the newest record
        span["dur"] = max(0.0, t_max - span["ts"])
        span["args"]["end"] = "open"
        evs.append(span)
    for rid, pid in lanes.items():
        evs.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": f"replica {rid}"}})
    return evs


def export_chrome(path: str, records=None, *,
                  replica_order=None) -> str:
    """Write the fleet lanes as Chrome-trace JSON in the envelope
    layout ``obs.tracing.export`` / ``obs.request_trace.export_chrome``
    use, so ``tools.trace_merge`` accepts it like any per-process span
    file."""
    if records is None:
        led = decisions.ledger()
        records = led.tail() if led is not None else []
    with open(path, "w") as f:
        f.write('{"displayTimeUnit":"ms","traceEvents":')
        f.write(json.dumps(
            to_chrome(records, replica_order=replica_order),
            separators=(",", ":"), default=str))
        f.write("}")
    return path


def export_fleet_timeline(out_path: str, *, records=None, traces=None,
                          replica_order=None) -> str:
    """The merged fleet timeline: replica lanes (ledger spans) overlaid
    with the cross-replica request chains (``obs.request_trace`` — its
    tiers ARE replica ids under the fleet router), merged through
    ``tools.trace_merge.merge_traces`` with explicit ``ts_offsets``.
    Both planes are wall-anchored on this host (ledger ``t_us`` =
    ``time.time_ns()/1e3``; traces anchor wall then advance by
    monotonic deltas), so the offsets are 0.0 here — the parameter is
    the alignment hook for replicas on OTHER hosts, whose ledger dumps
    carry their own clock."""
    import tempfile

    from ..tools import trace_merge
    from . import request_trace

    with tempfile.TemporaryDirectory(prefix="tdt-fleet-tl-") as td:
        fleet_path = os.path.join(td, "fleet_lanes.json")
        export_chrome(fleet_path, records, replica_order=replica_order)
        if traces is None:
            traces = request_trace.RING.recent(len(request_trace.RING))
        inputs, offsets = [fleet_path], [0.0]
        if traces:
            trace_path = os.path.join(td, "request_chains.json")
            request_trace.export_chrome(trace_path, traces)
            inputs.append(trace_path)
            offsets.append(0.0)
        trace_merge.merge_traces(inputs, list(range(len(inputs))),
                                 out_path, ts_offsets=offsets)
    return out_path


# ---------------------------------------------------------------------------
# selftest (tdt_lint --fleetobs + tier-1)


def selftest(seed: int = 0) -> list[str]:
    """Both-direction fleet anomaly check, no router needed: a clean
    2-replica feed judged against its own healthy band must stay
    quiet; an inflated replay (one replica's latencies x100 — both a
    p99 breach and a same-role skew) must be caught, with the event
    naming the p99 exemplar and carrying the ledger decisions from its
    window.  Perturbs the decisions singleton; restores it.  Returns
    problems (empty = pass)."""
    problems: list[str] = []
    prev_dec_enabled = decisions.enable(True)
    prev_led = decisions.install(
        decisions.DecisionLedger(cap=64, out_dir=None))
    try:
        def run(inflate: float) -> tuple[FleetStats, list]:
            union = ServeStats()
            fs = FleetStats(union=union, window_steps=4, record=False)
            a = fs.replica("p0", "prefill")
            b = fs.replica("p1", "prefill")
            for i in range(16):
                a.observe_ttft(10.0 + (i % 4),
                               exemplar=f"req-fleet-selftest-{seed}-a{i}")
                b.observe_ttft(10.0 + ((i + 1) % 4) * inflate,
                               exemplar=f"req-fleet-selftest-{seed}-b{i}")
            return fs, fs.on_step(4)

        # the healthy band from a clean run's own totals
        base, _ = run(1.0)
        t = dict(base.last_totals)
        bands = {
            "fleet_ttft_ms_p99": history.healthy_band(
                [t["fleet_ttft_ms_p99"] * 0.9,
                 t["fleet_ttft_ms_p99"] * 1.1], "lower"),
            "fleet_role_skew": history.healthy_band(
                [0.0, max(t["fleet_role_skew"], 0.05)], "lower"),
        }
        bands = {k: v for k, v in bands.items() if v is not None}
        if len(bands) < 2:
            return ["selftest: could not build both healthy bands from "
                    "the clean feed"]

        # a ledger decision inside the window, for events to carry
        decisions.record("quarantine_drain", step=2, replica="p1",
                         inputs={"selftest": True, "seed": seed})

        # direction 1: the clean replay must stay quiet
        fs_clean = FleetStats(union=ServeStats(), window_steps=4,
                              bands=bands, record=False)
        a = fs_clean.replica("p0", "prefill")
        b = fs_clean.replica("p1", "prefill")
        for i in range(16):
            a.observe_ttft(10.0 + (i % 4),
                           exemplar=f"req-fleet-selftest-{seed}-a{i}")
            b.observe_ttft(10.0 + ((i + 1) % 4),
                           exemplar=f"req-fleet-selftest-{seed}-b{i}")
        clean = fs_clean.on_step(4)
        if clean:
            problems.append(
                f"selftest: clean replay flagged "
                f"{[e.metric for e in clean]} — an identical feed must "
                f"stay inside its own band")

        # direction 2: the inflated replay must be caught on BOTH axes
        fs_bad = FleetStats(union=ServeStats(), window_steps=4,
                            bands=bands, record=False)
        a = fs_bad.replica("p0", "prefill")
        b = fs_bad.replica("p1", "prefill")
        for i in range(16):
            a.observe_ttft(10.0 + (i % 4),
                           exemplar=f"req-fleet-selftest-{seed}-a{i}")
            b.observe_ttft((10.0 + ((i + 1) % 4)) * 100.0,
                           exemplar=f"req-fleet-selftest-{seed}-b{i}")
        bad = fs_bad.on_step(4)
        hit = {e.metric for e in bad}
        for metric in ("fleet_ttft_ms_p99", "fleet_role_skew"):
            if metric not in hit:
                problems.append(
                    f"selftest: the 100x single-replica inflation did "
                    f"not breach {metric} — the fleet comparator is "
                    f"blind on that axis")
        for e in bad:
            if not e.exemplar:
                problems.append(
                    f"selftest: breach {e.metric} names no p99 "
                    f"exemplar")
            if not e.decisions:
                problems.append(
                    f"selftest: breach {e.metric} carries no ledger "
                    f"decisions from its window")
    finally:
        decisions.install(prev_led)
        decisions.enable(prev_dec_enabled)
    return problems
