"""The live telemetry endpoint: a stdlib HTTP plane over ``obs``.

``TDT_OBS_HTTP=<port>`` makes the engine start one process-wide
``ThreadingHTTPServer`` (daemon threads, port 0 = ephemeral) exposing:

- ``GET /metrics``   — Prometheus text: the registry exposition
  (``obs.to_prometheus``) followed by the live serving block
  (``obs.serve_stats`` quantile summaries, windowed rates, queue depth).
- ``GET /healthz``   — the serving-health snapshot as JSON
  (``Engine.health()`` when an engine is registered, else
  ``resilience.health_snapshot()``); **503** when the snapshot reports
  ``status != "ok"`` (an open circuit breaker), 200 otherwise — the
  load-balancer contract.
- ``GET /debug/flight``   — the current flight-ring tail
  (``obs.flight.recent``) as JSON: enabled state, step, event dicts and
  their ``describe()`` lines.  Bounded: the last 256 events by default,
  ``?n=`` up to 2048 — a full 100k-event ring must not be serialized
  into one response on a serving box.
- ``GET /debug/timeline`` — the per-collective attribution view.  With
  the continuous profiler armed (``TDT_PROFILE=1``) this serves the
  profiler's last completed window snapshot (``source: "profiler"``) —
  already reconstructed at the step boundary, so the scrape does no
  ring replay at all.  Otherwise the ring tail (last 4096 events,
  ``?n=`` caps lower/higher up to 16384) is reconstructed through
  ``obs.timeline`` (events grouped per recorded rank; live rank −1
  events form one stream), best-effort: a ring the credit replay cannot
  complete reports ``pending`` instead of erroring.
- ``GET /debug/profile`` — the continuous profiler's full snapshot
  (``obs.continuous``): open-window state, last completed window,
  lifetime sketch quantiles, retained anomalies, on-disk segments.
- ``GET /debug/serve``   — the live serve-stats snapshot plus, when the
  registered health source is a continuous-batching scheduler
  (``serve.Scheduler`` — it exposes ``debug_state()``), its queue /
  page-pool / slot / degradation-governor state, and the request-trace
  plane's p99 exemplar ids (TDT_TRACE=1).
- ``GET /debug/trace``   — the retained-trace ring listing;
  ``/debug/trace/<id>`` one trace's spans, overlay events and SLO
  attribution (``obs.request_trace``) — the SLO-debugging workflow's
  last hop: 503 -> exemplar id -> waterfall (docs/serving.md).
- ``GET /debug/fleet``   — the fleet observability plane
  (``TDT_FLEET_OBS=1``): the federation snapshot (merged sketches,
  per-replica drill-down, imbalance gauges, retained fleet anomalies)
  plus the control-decision ledger tail (``obs.decisions``; last 64
  records, ``?n=`` up to 512).  Disarmed processes answer a stub.

The health source registered via ``maybe_start`` / ``register_engine``
may be an :class:`~..models.engine.Engine` or a
:class:`~..serve.Scheduler` — anything with ``health()`` whose snapshot
carries ``status``; ``/healthz`` answers 503 whenever that status is
not ``"ok"`` (open breaker, sustained scheduler saturation).

Everything is read-only and unauthenticated — bind is loopback-only by
default (``TDT_OBS_HTTP_HOST`` overrides for pod networks).  With
``TDT_OBS_HTTP`` unset nothing starts and the engine path costs one env
read at construction.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_LOCK = threading.Lock()
_SERVER: "TelemetryServer | None" = None

# response bounds for the ring-backed debug endpoints (?n= clamps
# within these; the ring itself holds up to 100k events)
FLIGHT_DUMP_DEFAULT = 256
FLIGHT_DUMP_MAX = 2048
TIMELINE_DUMP_DEFAULT = 4096
TIMELINE_DUMP_MAX = 16384
# decision-ledger tail bounds for /debug/fleet (the ring holds up to
# TDT_DECISION_RING records; one scrape must stay bounded)
FLEET_DUMP_DEFAULT = 64
FLEET_DUMP_MAX = 512


def _query_n(query: str, default: int, cap: int) -> int:
    """The ``?n=`` override for a ring-tail endpoint, clamped to
    [1, cap]; absent/garbage values fall back to the default."""
    try:
        raw = urllib.parse.parse_qs(query).get("n", [None])[0]
        n = int(raw) if raw is not None else default
    except (ValueError, TypeError):
        n = default
    return max(1, min(int(n), cap))


def port_from_env() -> int | None:
    """The configured port, or None when the plane is off.  ``0`` asks
    for an ephemeral port (tests); unset/empty/off disables.  A value
    that parses as neither is a MISCONFIGURATION, not a disable: the
    operator asked for a plane and would get silence — warn loudly."""
    raw = os.environ.get("TDT_OBS_HTTP", "").strip().lower()
    if raw in ("", "off", "false", "no", "none"):
        return None
    try:
        return int(raw)
    except ValueError:
        import warnings

        warnings.warn(
            f"TDT_OBS_HTTP={raw!r} is not a port number; the telemetry "
            f"endpoint will NOT start")
        return None


class _Handler(BaseHTTPRequestHandler):
    server_version = "tdt-obs/1"

    # the handler reaches its TelemetryServer through the HTTPServer
    def _telemetry(self) -> "TelemetryServer":
        return self.server._telemetry  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # noqa: D102 — no stderr spam
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 — http.server API
        raw_path, _, query = self.path.partition("?")
        path = raw_path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200, self._telemetry().metrics_text(),
                           "text/plain; version=0.0.4")
            elif path == "/healthz":
                code, snap = self._telemetry().health()
                self._send(code, json.dumps(snap, indent=1, sort_keys=True,
                                            default=str),
                           "application/json")
            elif path == "/debug/flight":
                n = _query_n(query, FLIGHT_DUMP_DEFAULT, FLIGHT_DUMP_MAX)
                self._send(200, json.dumps(self._telemetry().flight_dump(n),
                                           default=str),
                           "application/json")
            elif path == "/debug/timeline":
                n = _query_n(query, TIMELINE_DUMP_DEFAULT, TIMELINE_DUMP_MAX)
                self._send(200,
                           json.dumps(self._telemetry().timeline_dump(n),
                                      default=str),
                           "application/json")
            elif path == "/debug/profile":
                self._send(200, json.dumps(self._telemetry().profile_dump(),
                                           default=str),
                           "application/json")
            elif path == "/debug/diff":
                self._send(200, json.dumps(self._telemetry().diff_dump(),
                                           default=str),
                           "application/json")
            elif path == "/debug/serve":
                self._send(200, json.dumps(self._telemetry().serve_dump(),
                                           default=str),
                           "application/json")
            elif path == "/debug/fleet":
                n = _query_n(query, FLEET_DUMP_DEFAULT, FLEET_DUMP_MAX)
                self._send(200, json.dumps(self._telemetry().fleet_dump(n),
                                           default=str),
                           "application/json")
            elif path == "/debug/trace" or path.startswith("/debug/trace/"):
                trace_id = path[len("/debug/trace/"):] \
                    if path.startswith("/debug/trace/") else None
                code, body = self._telemetry().trace_dump(trace_id)
                self._send(code, json.dumps(body, default=str),
                           "application/json")
            else:
                self._send(404, json.dumps({
                    "error": f"unknown path {path!r}",
                    "endpoints": ["/metrics", "/healthz", "/debug/flight",
                                  "/debug/timeline", "/debug/profile",
                                  "/debug/diff", "/debug/serve",
                                  "/debug/fleet", "/debug/trace"],
                }), "application/json")
        except BrokenPipeError:
            pass
        except Exception as e:  # a debug endpoint must never kill the plane
            try:
                self._send(500, json.dumps({"error": f"{type(e).__name__}: "
                                                     f"{e}"}),
                           "application/json")
            except Exception:
                pass


class TelemetryServer:
    """One bound HTTP server on a daemon thread; ``stop()`` joins it."""

    def __init__(self, port: int, host: str | None = None,
                 engine=None):
        self.host = host or os.environ.get("TDT_OBS_HTTP_HOST",
                                           "127.0.0.1")
        self._httpd = ThreadingHTTPServer((self.host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._telemetry = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._engine_ref = (lambda: None)
        if engine is not None:
            self.register_engine(engine)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tdt-obs-http",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def register_engine(self, engine) -> None:
        """Weakly attach the engine whose ``health()`` backs ``/healthz``
        (the latest registered engine wins; the server must not keep a
        dead engine's cache trees alive)."""
        self._engine_ref = weakref.ref(engine)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # -- endpoint bodies ---------------------------------------------------

    def metrics_text(self) -> str:
        from . import continuous, decisions, dump_prometheus, fleet_stats
        from . import serve_stats

        return (dump_prometheus() + serve_stats.STATS.to_prometheus()
                + continuous.to_prometheus()
                + fleet_stats.to_prometheus()
                + decisions.to_prometheus())

    def health(self) -> tuple[int, dict]:
        engine = self._engine_ref()
        if engine is not None:
            snap = engine.health()
        else:
            from .. import resilience

            snap = resilience.health_snapshot()
        code = 200 if snap.get("status") == "ok" else 503
        return code, snap

    def serve_dump(self) -> dict:
        """The scheduler inspection endpoint (``/debug/serve``): the
        live serve-stats snapshot plus — when the registered health
        source is a scheduler (or anything exposing ``debug_state()``)
        — its queue / pool / slot / governor state, plus the request-
        trace plane's exemplar ids (TDT_TRACE=1): the p99 buckets of
        the TTFT and request-latency sketches name the retained traces
        that landed there — the "show me a p99 request" entry point
        (follow with ``/debug/trace/<id>``)."""
        from . import request_trace, serve_stats

        out: dict = {"serve_stats": serve_stats.STATS.snapshot()}
        src = self._engine_ref()
        debug = getattr(src, "debug_state", None)
        if callable(debug):
            out["scheduler"] = debug()
        out["trace"] = {
            "enabled": request_trace.enabled(),
            "retained": len(request_trace.RING),
            "exemplars": {
                "ttft_ms_p99": serve_stats.STATS.ttft_ms.exemplar(0.99),
                "request_ms_p99":
                    serve_stats.STATS.request_ms.exemplar(0.99),
            },
        }
        return out

    def trace_dump(self, trace_id: str | None = None) -> tuple[int, dict]:
        """``/debug/trace`` (ring listing) and ``/debug/trace/<id>``
        (one retained trace: spans, events, SLO attribution)."""
        from . import request_trace

        if not trace_id:
            return 200, {
                "enabled": request_trace.enabled(),
                "cap": request_trace.RING.cap,
                "retained": len(request_trace.RING),
                "ids": request_trace.RING.ids(),
            }
        tr = request_trace.RING.get(trace_id)
        if tr is None:
            return 404, {
                "error": f"trace {trace_id!r} not retained (ring keeps "
                         f"the last {request_trace.RING.cap} completed "
                         f"traces)",
                "ids": request_trace.RING.ids()[-16:],
            }
        return 200, tr.to_dict()

    def flight_dump(self, n: int = FLIGHT_DUMP_DEFAULT) -> dict:
        from . import flight

        n = max(1, min(int(n), FLIGHT_DUMP_MAX))
        evs = flight.recent(n)
        return {
            "enabled": flight.enabled(),
            "keep_steps": flight.keep_steps(),
            "n": n,
            "events": [ev.to_dict() for ev in evs],
            "lines": [ev.describe() for ev in evs],
        }

    def profile_dump(self) -> dict:
        """``/debug/profile``: the continuous profiler's snapshot
        (``obs.continuous``).  Disarmed processes answer a stub rather
        than 404, so a dashboard can probe for the capability."""
        from . import continuous

        if not continuous.enabled():
            return {"enabled": False,
                    "hint": "set TDT_PROFILE=1 (docs/observability.md)"}
        prof = continuous.profiler()
        if prof is None:      # armed but no step boundary reached yet
            return {"enabled": True, "windows_total": 0,
                    "anomalies_total": 0, "last_window": None}
        return prof.snapshot()

    def diff_dump(self) -> dict:
        """``/debug/diff``: the latest anomaly's window-vs-baseline
        attribution (``obs.diff`` via ``obs.anomaly``), plus the fleet
        plane's latest attributed breach when armed.  Disarmed
        processes answer a stub rather than 404, the
        ``/debug/profile`` rule.  Scrape-safe during window rotation:
        events are frozen and their attribution dicts are built once
        at detection time, never mutated after publish."""
        from . import anomaly, continuous, fleet_stats

        if not continuous.enabled():
            return {"enabled": False,
                    "hint": "set TDT_PROFILE=1 (docs/observability.md)"}
        ev = anomaly.latest_attributed()
        out = {
            "enabled": True,
            "anomalies_total": anomaly.total(),
            "anomaly": ev.to_dict() if ev else None,
            "diff": ev.diff if ev else None,
        }
        if ev is None:
            out["hint"] = ("no attributed anomaly yet — breaches gain "
                           "a diff once a healthy baseline window has "
                           "rotated")
        fleet = fleet_stats.current()
        if fleet is not None:
            fev = next((e for e in reversed(fleet.recent_events())
                        if e.diff), None)
            if fev is not None:
                out["fleet_anomaly"] = fev.to_dict()
        return out

    def fleet_dump(self, n: int = FLEET_DUMP_DEFAULT) -> dict:
        """``/debug/fleet``: the federation plane's snapshot (merged
        sketches, per-replica drill-down, imbalance gauges, retained
        fleet anomalies) plus the decision-ledger tail (last ``n``
        records, ``?n=`` clamped to [1, 512]).  Disarmed processes
        answer a stub rather than 404, the ``/debug/profile`` rule."""
        from . import decisions, fleet_stats

        n = max(1, min(int(n), FLEET_DUMP_MAX))
        return {
            "fleet_stats": fleet_stats.snapshot_dump(),
            "decisions": decisions.tail_dump(n),
        }

    def timeline_dump(self, n: int = TIMELINE_DUMP_DEFAULT) -> dict:
        """The attribution view.  Armed (``TDT_PROFILE=1``) with a
        completed window, serve the profiler's own snapshot — the
        reconstruction already happened incrementally at the step
        boundary; a scrape must not replay the ring again.  Otherwise
        reconstruct the ring TAIL (last ``n`` events) through
        ``obs.timeline``: events grouped by recorded rank (a
        deterministic capture harness writes rank >= 0; live ring
        events carry rank −1 and form one stream).  Partial rings
        reconstruct as far as credits allow (``pending``)."""
        from . import continuous, flight, timeline

        prof = continuous.profiler() if continuous.enabled() else None
        if prof is not None:
            last = prof.last_window()
            if last is not None:
                return {
                    "enabled": flight.enabled(),
                    "source": "profiler",
                    "window": last,
                }
        n = max(1, min(int(n), TIMELINE_DUMP_MAX))
        evs = flight.recent(n)
        ranks = sorted({ev.rank for ev in evs if ev.rank >= 0})
        if ranks:
            streams = [[ev for ev in evs if ev.rank == r] for r in ranks]
        else:
            streams = [list(evs)]
        try:
            tl = timeline.reconstruct(streams, kernel="flight-ring")
            return {
                "enabled": flight.enabled(),
                "source": "ring",
                "n": n,
                "ranks": tl.n,
                "events": len(evs),
                "critical_us": tl.critical_us,
                "pct_sol": tl.pct_sol,
                "stalled": tl.stalled,
                "pending": list(tl.pending),
                "waits": [w.describe() for w in tl.waits],
                "table": timeline.format_table(tl),
            }
        except Exception as e:
            return {
                "enabled": flight.enabled(),
                "source": "ring",
                "n": n,
                "events": len(evs),
                "error": f"{type(e).__name__}: {e}",
                "lines": [ev.describe() for ev in evs[-64:]],
            }


def start(port: int | None = None, engine=None) -> TelemetryServer:
    """Start (or return) the process-wide telemetry server.  ``port``
    defaults to ``TDT_OBS_HTTP``; raises when neither is set."""
    global _SERVER
    with _LOCK:
        if _SERVER is not None:
            if engine is not None:
                _SERVER.register_engine(engine)
            return _SERVER
        if port is None:
            port = port_from_env()
        if port is None:
            raise ValueError(
                "no port: pass one or set TDT_OBS_HTTP=<port>")
        _SERVER = TelemetryServer(port, engine=engine)
        return _SERVER


def maybe_start(engine=None) -> TelemetryServer | None:
    """The engine-construction hook: start the plane iff ``TDT_OBS_HTTP``
    is set (one env read when unset — PR-4 behavior is otherwise
    untouched).  With the env UNSET this is a strict no-op even when a
    server is already running: an explicitly-started plane (``start()``
    with no engine) keeps its resilience-snapshot ``/healthz`` and must
    not be silently adopted — and later torn down — by an engine the
    operator never wired to it."""
    if port_from_env() is None:
        return None
    try:
        return start(engine=engine)
    except OSError:
        # the port being taken (another serving process on the box) must
        # not stop the engine from serving; the operator sees it in the
        # scrape gap, not as a dead engine
        return None


def running() -> TelemetryServer | None:
    return _SERVER


def stop() -> None:
    """Stop the process-wide server (idempotent)."""
    global _SERVER
    with _LOCK:
        srv, _SERVER = _SERVER, None
    if srv is not None:
        srv.stop()


def release(engine) -> None:
    """Engine-owned shutdown: stop the plane iff ``engine`` is the
    registered health source (``Engine.close``); other engines keep it.
    The check-and-detach happens under ``_LOCK`` so a concurrent
    ``start()`` registering another engine cannot lose its plane to a
    stale release."""
    global _SERVER
    with _LOCK:
        srv = _SERVER
        if srv is None or srv._engine_ref() is not engine:
            return
        _SERVER = None
    srv.stop()
