"""Kernel cost attribution: one shared flop/byte/transcendental source.

The reference attaches ``launch_metadata`` flop and byte counts to every
overlapped kernel (``allgather_gemm.py:132-143``) so its profiler can
label kernel cost in the merged timeline.  Here the same numbers feed
THREE consumers that previously each had (or lacked) their own
arithmetic:

- the fused ops' ``pallas_call(cost_estimate=...)`` — Mosaic/XLA use the
  estimate for scheduling, and profilers surface it per kernel
  (:func:`pallas_cost`);
- ``tools.perf_model``'s speed-of-light estimates — the roofline the
  watchdog derives deadlines from and benches report "% of SOL" against
  (:func:`sol_ms`);
- the flight-recorder timeline (``obs.timeline``) — recorded protocol
  events are placed on a model clock whose compute/wire durations come
  from these same counts, so the achieved-vs-SOL column of
  ``scripts/obs_report.py --timeline`` and the watchdog budget can never
  quote different flop counts for the same kernel.

Conventions: ``flops`` counts multiply-adds as 2 ops (matmul = 2·M·N·K);
``bytes_accessed`` is HBM traffic (operand reads + result writes +
DMA-staged traffic for the fused collectives); ``transcendentals``
counts exp/tanh evaluations (the softmax VPU term that makes attention
VPU-bound — see docs/perf.md).
"""

from __future__ import annotations

import dataclasses


def _itemsize(dtype) -> int:
    import jax.numpy as jnp

    return int(jnp.dtype(dtype).itemsize)


@dataclasses.dataclass(frozen=True)
class KernelCost:
    """Flop/byte/transcendental counts of one kernel invocation (per
    device).  ``wire_bytes`` is the portion of ``bytes_accessed`` that
    crosses ICI (0 for local kernels) — the collective half of a fused
    op's roofline.  ``dcn_bytes`` is the portion that crosses the
    inter-slice (DCN) wire — the two-level families (ISSUE 10) split
    their wire per class so every consumer (watchdog deadline, timeline
    pct_sol, report) charges each level its own wire speed."""

    flops: int
    bytes_accessed: int
    transcendentals: int = 0
    wire_bytes: int = 0
    dcn_bytes: int = 0

    def scaled(self, k: float) -> "KernelCost":
        return KernelCost(int(self.flops * k), int(self.bytes_accessed * k),
                          int(self.transcendentals * k),
                          int(self.wire_bytes * k),
                          int(self.dcn_bytes * k))


def pallas_cost(cost: KernelCost):
    """``pl.CostEstimate`` for ``pallas_call(cost_estimate=...)``; None on
    jax builds that predate the parameter (the call site passes it
    through — None is the default)."""
    try:
        from jax.experimental import pallas as pl
    except Exception:  # pragma: no cover - jax always importable here
        return None
    ce = getattr(pl, "CostEstimate", None)
    if ce is None:
        return None
    return ce(flops=int(cost.flops), bytes_accessed=int(cost.bytes_accessed),
              transcendentals=int(cost.transcendentals))


def sol_ms(cost: KernelCost, device_kind: str | None = None) -> float:
    """Roofline time of ``cost`` on one chip: max(MXU, HBM, ICI, DCN)
    terms — the same max() shape as ``tools.perf_model.gemm_sol_ms``,
    extended with a wire term PER WIRE CLASS: ``wire_bytes`` is charged
    at ICI speed, ``dcn_bytes`` at the (calibrated) DCN speed
    (``perf_model.dcn_gbps``).  Pricing every hop as ICI would quote
    multi-slice kernels a deadline/pct_sol the slow wire can never
    meet — the dishonesty this split removes (ISSUE 10)."""
    from ..tools import perf_model

    spec = perf_model.chip_spec(device_kind)
    t_flops = cost.flops / (spec.bf16_tflops * 1e12)
    t_mem = (cost.bytes_accessed - cost.wire_bytes - cost.dcn_bytes) \
        / (spec.hbm_gbps * 1e9)
    t_wire = cost.wire_bytes / (spec.ici_gbps * 1e9)
    t_dcn = cost.dcn_bytes / (perf_model.dcn_gbps() * 1e9)
    return max(t_flops, t_mem, t_wire, t_dcn) * 1e3


# ---------------------------------------------------------------------------
# per-kernel calculators (per DEVICE, at the shapes the builders see)


def matmul(m: int, n: int, k: int, dtype, out_dtype=None) -> KernelCost:
    """Plain blocked matmul C[m,n] = A[m,k] @ B[k,n] (``ops.matmul`` and
    the inner pipeline of every fused GEMM)."""
    ib = _itemsize(dtype)
    ob = _itemsize(out_dtype if out_dtype is not None else dtype)
    return KernelCost(
        flops=2 * m * n * k,
        bytes_accessed=ib * (m * k + k * n) + ob * m * n,
    )


def ag_gemm(m_loc: int, k: int, n_loc: int, num_ranks: int, dtype,
            out_dtype=None) -> KernelCost:
    """Fused AllGather-GEMM per device: the consumer matmul runs over the
    FULL gathered A (n·m_loc rows), and (n-1) A-shards transit this
    rank's ICI links (ring: each chunk forwarded once per hop)."""
    n = num_ranks
    mm = matmul(n * m_loc, n_loc, k, dtype, out_dtype)
    wire = (n - 1) * m_loc * k * _itemsize(dtype)
    return KernelCost(
        flops=mm.flops,
        # gathered-A workspace write + matmul traffic + wire staging
        bytes_accessed=mm.bytes_accessed + n * m_loc * k * _itemsize(dtype)
        + wire,
        wire_bytes=wire,
    )


def gemm_rs(m_loc: int, k_loc: int, n_dim: int, num_ranks: int, dtype,
            out_dtype=None) -> KernelCost:
    """Fused GEMM-ReduceScatter per device: n chunk matmuls over the local
    K-shard plus the travelling-partial adds; each of the (n-1) forwarded
    partials crosses one ICI hop."""
    n = num_ranks
    ob = _itemsize(out_dtype if out_dtype is not None else dtype)
    mm = matmul(n * m_loc, n_dim, k_loc, dtype, out_dtype)
    add_flops = (n - 1) * m_loc * n_dim
    wire = (n - 1) * m_loc * n_dim * ob
    return KernelCost(
        flops=mm.flops + add_flops,
        # matmul traffic + recv/send partial staging + wire
        bytes_accessed=mm.bytes_accessed
        + 2 * (n - 1) * m_loc * n_dim * ob + wire,
        wire_bytes=wire,
    )


def gemm_ar(m_loc: int, k_loc: int, n_dim: int, num_ranks: int, dtype,
            out_dtype=None) -> KernelCost:
    """Fused GEMM-AllReduce: the GEMM-RS phase plus the AG ring returning
    every reduced chunk to every rank (2(n-1)/n of the output per link)."""
    n = num_ranks
    ob = _itemsize(out_dtype if out_dtype is not None else dtype)
    rs = gemm_rs(m_loc, k_loc, n_dim, n, dtype, out_dtype)
    ag_wire = (n - 1) * m_loc * n_dim * ob
    return KernelCost(
        flops=rs.flops,
        bytes_accessed=rs.bytes_accessed + ag_wire
        + (n - 1) * m_loc * n_dim * ob,
        wire_bytes=rs.wire_bytes + ag_wire,
    )


def flash_attention(b: int, h: int, seq_q: int, seq_kv: int, d: int,
                    causal: bool, dtype) -> KernelCost:
    """Prefill flash kernel (also the ring-attention chunk kernel at chunk
    shapes — ``sp_attention`` folds one (seq_q, seq_c) tile per station).
    Causal halves the score work; transcendentals count the exp per
    score entry (the VPU term that bounds this kernel, docs/perf.md)."""
    ib = _itemsize(dtype)
    scores = b * h * seq_q * seq_kv
    if causal:
        scores //= 2
    return KernelCost(
        flops=4 * scores * d,
        bytes_accessed=ib * (b * h * seq_q * d * 2          # q read, o write
                             + 2 * b * h * seq_kv * d),     # k, v reads
        transcendentals=scores,
    )


def decode_attention(b: int, h: int, hk: int, seq_kv: int, d: int,
                     kv_dtype) -> KernelCost:
    """Split-KV / fused / paged decode kernels (one token against the
    cache): KV-bandwidth bound — bytes are dominated by streaming the
    (B, Hkv, S, D) cache once."""
    ib = _itemsize(kv_dtype)
    scores = b * h * seq_kv
    return KernelCost(
        flops=4 * scores * d,
        bytes_accessed=2 * b * hk * seq_kv * d * ib        # K + V stream
        + b * h * d * ib * 2,                               # q read, o write
        transcendentals=scores,
    )


def fused_attn_decode(b: int, k_dim: int, h: int, hk: int, seq_kv: int,
                      d: int, kv_dtype) -> KernelCost:
    """The attention-side decode megakernel (``ops.fused_decode``): the
    per-head qkv projection GEMMs plus streaming the paged cache once —
    the fused form of (qkv GEMM + rope/norm + append + paged decode).
    The qkv weight is read once per kv-head GROUP (the head-outer grid
    keeps each head's columns resident across the batch loop)."""
    ib = _itemsize(kv_dtype)
    qkv_cols = (h + 2 * hk) * d
    att = decode_attention(b, h, hk, seq_kv, d, kv_dtype)
    return KernelCost(
        flops=att.flops + 2 * b * k_dim * qkv_cols,
        bytes_accessed=att.bytes_accessed
        + ib * (k_dim * qkv_cols          # weight columns, once per head
                + b * k_dim               # activation rows
                + 2 * b * hk * d),        # the appended K/V token slots
        # rope adds 2 transcendentals per rotated (q + k) element
        transcendentals=att.transcendentals + 2 * b * (h + hk) * d,
    )


def fused_mlp_ar(b: int, k_in: int, k_loc: int, n_dim: int,
                 num_ranks: int, dtype, out_dtype=None, *,
                 swiglu: bool = True) -> KernelCost:
    """The semaphore-chained MLP/o-proj + two-shot AllReduce megakernel
    per device: [gate/up GEMM + SwiGLU when ``swiglu``] + the down-proj
    chunk GEMMs + travelling-partial adds, with 2(n-1)/n of the (B,
    n_dim) output crossing ICI (ring RS + AG phases)."""
    n = num_ranks
    ib = _itemsize(dtype)
    ob = _itemsize(out_dtype if out_dtype is not None else dtype)
    dn = matmul(b, n_dim, k_loc, dtype, out_dtype)
    flops = dn.flops + (n - 1) * b * (n_dim // max(n, 1))
    nbytes = dn.bytes_accessed
    transc = 0
    if swiglu:
        up = matmul(b, 2 * k_loc, k_in, dtype, out_dtype)
        flops += up.flops + 3 * b * k_loc        # silu mul fold
        nbytes += up.bytes_accessed + 3 * b * k_loc * ob
        transc = b * k_loc                       # one exp per silu entry
    wire = 2 * (n - 1) * b * (n_dim // max(n, 1)) * ob
    return KernelCost(
        flops=flops,
        bytes_accessed=nbytes + 2 * wire,        # recv/send staging + wire
        transcendentals=transc,
        wire_bytes=wire,
    )


def persistent_decode(layers: int, b: int, k_dim: int, h: int, hk: int,
                      seq_kv: int, d: int, f_loc: int, num_ranks: int,
                      kv_dtype) -> KernelCost:
    """The persistent multi-layer decode megakernel
    (``ops.persistent_decode``): per device, L x (the attention-side
    cell + the o-proj chained AR + the SwiGLU-MLP chained AR) — composed
    from the per-layer calculators so the watchdog deadline, Mosaic cost
    estimate and the timeline price the chain exactly as L of the PR-8
    kernels with the host boundaries removed."""
    att = fused_attn_decode(b, k_dim, h, hk, seq_kv, d, kv_dtype)
    # h/hk/f_loc are PER-DEVICE here (the builder's shapes), so the
    # o-proj's per-rank contraction depth is the full local width h*d —
    # dividing it by num_ranks again would under-price the GEMM n-fold
    oproj = fused_mlp_ar(b, h * d, h * d, k_dim, num_ranks, kv_dtype,
                         swiglu=False)
    mlp = fused_mlp_ar(b, k_dim, f_loc, k_dim, num_ranks, kv_dtype,
                       swiglu=True)
    per_layer = KernelCost(
        flops=att.flops + oproj.flops + mlp.flops,
        bytes_accessed=att.bytes_accessed + oproj.bytes_accessed
        + mlp.bytes_accessed,
        transcendentals=att.transcendentals + oproj.transcendentals
        + mlp.transcendentals,
        wire_bytes=att.wire_bytes + oproj.wire_bytes + mlp.wire_bytes,
    )
    return per_layer.scaled(layers)


def all_gather(m_loc: int, r: int, num_ranks: int, dtype) -> KernelCost:
    """Eager AG per device (ring accounting — push/bidir move the same
    total bytes over more links): (n-1) shards transit this rank's ICI
    links; HBM pays the gathered write plus the local shard read."""
    n = num_ranks
    ib = _itemsize(dtype)
    shard = m_loc * r * ib
    wire = (n - 1) * shard
    return KernelCost(
        flops=0,
        bytes_accessed=(n + 1) * shard + wire,
        wire_bytes=wire,
    )


def reduce_scatter(m: int, r: int, num_ranks: int, dtype) -> KernelCost:
    """Ring RS per device: (n-1) travelling-partial hops of the m/n
    chunk, one add per forwarded element."""
    n = num_ranks
    ib = _itemsize(dtype)
    chunk = (m // max(n, 1)) * r
    wire = (n - 1) * chunk * ib
    return KernelCost(
        flops=(n - 1) * chunk,
        bytes_accessed=m * r * ib + chunk * ib + 2 * wire,
        wire_bytes=wire,
    )


def all_reduce(m: int, r: int, num_ranks: int, dtype) -> KernelCost:
    """Two-shot AR per device: the RS phase plus the AG ring returning
    every reduced chunk — 2(n-1)/n of the payload per link."""
    n = num_ranks
    ib = _itemsize(dtype)
    rs = reduce_scatter(m, r, n, dtype)
    ag_wire = (n - 1) * (m // max(n, 1)) * r * ib
    return KernelCost(
        flops=rs.flops,
        bytes_accessed=rs.bytes_accessed + 2 * ag_wire,
        wire_bytes=rs.wire_bytes + ag_wire,
    )


def quantized_wire(rows: int, h: int, num_ranks: int, wire_dtype: str,
                   kind: str = "all_gather") -> KernelCost:
    """A quantized collective at its packed-u8 wire geometry: the same
    ring/exchange protocols over ``packed_wire_bytes`` rows (payload
    byte per element + the 128-lane scale sidecar), plus the pack/unpack
    pass over the full-precision payload."""
    packed = packed_wire_bytes(rows, h, wire_dtype)
    n = num_ranks
    wire = (n - 1) * packed // max(n, 1) if kind != "all_gather" \
        else (n - 1) * packed
    return KernelCost(
        flops=2 * rows * h,               # absmax + scale multiply
        bytes_accessed=2 * rows * h * 2 + packed + wire,
        wire_bytes=wire,
    )


def packed_wire_bytes(rows: int, h: int, wire_dtype: str) -> int:
    """Bytes ``rows`` H-wide rows occupy on a QUANTIZED wire (payload
    byte per element + the 128-lane scale sidecar per row —
    ``lang.quant.packed_width``): the accounting the quantized
    collective entries report to ``comm_wire_bytes`` and ``bench.py
    wire`` gates against the bf16 baseline (<= 0.55x at serving
    widths)."""
    from ..lang import quant

    return rows * quant.packed_width(h, wire_dtype)


def all_to_all(rows: int, h: int, num_ranks: int, dtype) -> KernelCost:
    """EP A2A push kernel per device: every local row is read once and
    pushed to its destination zone; peers' rows land in our zones.
    ``rows`` is the per-device token count (zone capacity bound)."""
    ib = _itemsize(dtype)
    wire = rows * h * ib
    return KernelCost(
        flops=0,
        bytes_accessed=2 * rows * h * ib + wire,
        wire_bytes=wire,
    )


def _hier_cost(ici: int, dcn: int, extra_hbm: int = 0,
               flops: int = 0) -> KernelCost:
    return KernelCost(
        flops=flops,
        bytes_accessed=extra_hbm + ici + dcn,
        wire_bytes=ici,
        dcn_bytes=dcn,
    )


def hier_all_gather(m_loc: int, r: int, n_in: int, n_out: int,
                    dtype) -> KernelCost:
    """Two-level AG per chip (``comm.hierarchical``): inner ring
    forwards (n_in-1) shards on ICI, the outer broadcast lands (n_out-1)
    slice blocks over DCN; HBM pays the gathered write."""
    ib = _itemsize(dtype)
    shard = m_loc * r * ib
    return _hier_cost((n_in - 1) * shard, (n_out - 1) * n_in * shard,
                      extra_hbm=n_out * n_in * shard)


def hier_reduce_scatter(m_partial: int, r: int, n_in: int, n_out: int,
                        dtype) -> KernelCost:
    ib = _itemsize(dtype)
    chunk = (m_partial // max(n_in, 1)) * r * ib
    add_flops = (n_in - 1) * (m_partial // max(n_in, 1)) * r
    return _hier_cost((n_in - 1) * chunk,
                      (n_out - 1) * chunk // max(n_out, 1),
                      extra_hbm=2 * (n_in - 1) * chunk, flops=add_flops)


def hier_all_reduce(m: int, r: int, n_in: int, n_out: int,
                    dtype) -> KernelCost:
    """Two-level AR (RS ∘ AG) per chip: 2(n_in-1)/n_in of the partial on
    ICI, 2(n_out-1)/n_out of the 1/n_in partial on DCN — the RS∘AG bound
    ``bench.py hier`` gates."""
    ib = _itemsize(dtype)
    partial = m * r * ib
    ici = 2 * (n_in - 1) * partial // max(n_in, 1)
    dcn = 2 * (n_out - 1) * (partial // max(n_in, 1)) // max(n_out, 1)
    add_flops = (n_in - 1) * (m // max(n_in, 1)) * r + \
        (n_out - 1) * (m // max(n_in, 1)) * r
    return _hier_cost(ici, dcn, extra_hbm=2 * partial, flops=add_flops)


def hier_all_to_all(rows: int, h: int, n_in: int, n_out: int,
                    dtype) -> KernelCost:
    """Scheduled EP A2A per chip: the DCN phase ships (n_out-1) FIXED
    zero-padded payload-sized blocks (static shapes — the bytes move
    regardless of routing); up to the n_out merged blocks redistribute
    on ICI."""
    ib = _itemsize(dtype)
    payload = rows * h * ib
    return _hier_cost(n_out * payload, (n_out - 1) * payload,
                      extra_hbm=2 * n_out * payload)


# the registry the report and timeline consume: family -> calculator.
# (sp_attention and flash_decode ride the attention-family kernels they
# are built from — flash_attention at chunk shapes, decode_attention at
# per-rank cache shapes.)
FAMILY_COSTS = {
    "matmul": matmul,
    "ag_gemm": ag_gemm,
    "gemm_rs": gemm_rs,
    "gemm_ar": gemm_ar,
    # the eager collective families (ISSUE 15 completeness: every
    # analysis.registry family prices through ONE flop/byte source —
    # these fold the perf_model wire arithmetic into KernelCost form)
    "allgather": all_gather,
    "reduce_scatter": reduce_scatter,
    "allreduce": all_reduce,
    "quantized_wire": quantized_wire,
    "flash_attention": flash_attention,
    "sp_attention": flash_attention,
    "decode_attention": decode_attention,
    "flash_decode": decode_attention,
    "all_to_all": all_to_all,
    # the decode megakernels (ops/fused_decode): one flop/byte truth for
    # their pallas cost estimates, the watchdog deadline model, and the
    # timeline reconstructor — like every other family here
    "fused_attn_decode": fused_attn_decode,
    "fused_mlp_ar": fused_mlp_ar,
    # the persistent multi-layer decode loop (ops/persistent_decode):
    # L chained (attention + o-proj AR + MLP AR) layers in one launch
    "persistent_decode": persistent_decode,
    # the two-level (ICI x DCN) families (ISSUE 10): wire split per
    # class, so deadlines/pct_sol charge each level its own wire
    "hier_all_gather": hier_all_gather,
    "hier_reduce_scatter": hier_reduce_scatter,
    "hier_all_reduce": hier_all_reduce,
    "hier_all_to_all": hier_all_to_all,
}
