"""Per-request distributed tracing with cross-tier SLO attribution.

``serve_stats`` can say TTFT p99 regressed; nothing in the aggregate
plane can say WHICH hop of WHICH request ate the budget.  This module is
the per-request measurement substrate (T3's chunk/arrival-granular
tracking discipline, PAPERS.md, applied to the serving path): a
:class:`TraceContext` is minted at ``Scheduler.submit`` and rides
``Request.trace`` across every hop of the multi-tier pipeline —

    queue wait -> prefill chunk(s) -> [handoff wait -> extract ->
    transfer (wire / stamp-verify split) -> adopt] -> decode window(s)
    -> done | failed | shed            (preemption/recompute and the
                                        retry/re-prefill rungs ride
                                        along as spans + annotations)

The chain is **gapless by construction**: ``begin(name)`` closes the
current span and opens the next AT THE SAME TIMESTAMP, and ``end()``
closes the last — so the spans partition [submit, terminal] exactly and
:func:`attribute_request` decomposes end-to-end latency into named phase
budgets with NO silent gap (``tests/test_request_trace.py`` and
``scripts/tdt_lint.py --trace`` pin the equality).  Overlay events
(``event(...)`` intervals: DCN wire time, stamp-verify time, retry
rungs) carry the sub-phase detail; the attributor reports them as the
per-phase exposed-vs-overlapped split using the same interval arithmetic
as the overlap report (``obs.report``).

Timebase: every timestamp is WALL-anchored microseconds — the anchor is
``time.time_ns() // 1000`` at mint, advanced by ``perf_counter_ns``
deltas (monotonic) — exactly the clock ``obs.tracing`` spans use, so a
request trace and the process span trace merge into ONE Chrome timeline
(:func:`export_chrome` + ``tools.trace_merge``).  Cross-process tiers
align through the same ``ts_offsets`` path the flight recorder uses
(``obs.timeline.align_clocks`` -> ``merge_traces(ts_offsets=...)``);
in-process tiers (the SimBackend harnesses) share the clock, offset 0.

Everything is OFF by default (``TDT_TRACE=1`` or :func:`enable` — the
TDT_OBS discipline): with the flag unset no context is ever minted, the
scheduler's per-hop sites see ``req.trace is None`` and the serve loop
is byte-identical.  ``obs.suppress()`` is honored at mint time, so
autotune sweeps and bench warmups never land in the ring or the
exemplars.  Completed traces retire into a bounded ring
(``TDT_TRACE_RING``, default 256) served by ``/debug/trace/<id>``; the
``ttft_ms`` / ``request_ms`` p99 buckets carry exemplar trace ids
(``obs.serve_stats.QuantileSketch``), making "show me a p99 request" a
one-call lookup.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import os
import threading
import time
from collections import OrderedDict

# hard cap on chain spans per trace: beyond it new hops COALESCE into
# the open span (a `coalesced` tag counts them) instead of growing the
# list — the chain stays gapless and memory stays bounded even for a
# pathological ten-thousand-window decode
MAX_SPANS = 512

DEFAULT_RING = 256

# span name -> attribution phase (anything unlisted is its own phase)
PHASE_OF = {
    "queue_wait": "queue",
    "prefill_chunk": "prefill",
    "handoff_wait": "handoff",
    "handoff_extract": "handoff",
    "handoff_transfer": "handoff",
    "adopt": "handoff",
    "decode_wait": "decode",
    "decode_window": "decode",
    "preempted": "preempted",
}

# overlay event name -> phase (the wire/verify split of a handoff
# transfer; retry rungs are zero-duration annotations and carry no time)
EVENT_PHASE_OF = {
    "handoff_wire": "handoff",
    "stamp_verify": "handoff",
}

_ids = itertools.count()

_pkg_cache: list = []


def _suppressed() -> bool:
    # the obs package's thread-local suppress() gate, read through a
    # memoized module ref (obs imports this module at package init, so a
    # top-level `from .. import obs` would be circular)
    if not _pkg_cache:
        import sys

        _pkg_cache.append(sys.modules[__package__])
    return _pkg_cache[0]._suppressed()


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_TRACE")


_ENABLED = _env_enabled()


def enabled() -> bool:
    """Whether request traces are minted (``TDT_TRACE=1`` or
    :func:`enable`, and not inside an ``obs.suppress()`` block on this
    thread — sweep/warmup traffic stays out of the ring)."""
    return _ENABLED and not _suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn the trace plane on/off at runtime; ``None`` re-reads
    ``TDT_TRACE``.  Returns the PREVIOUS state (so callers can restore)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return prev


@dataclasses.dataclass
class Span:
    """One chain hop.  ``t1_us`` is None while the span is open."""

    name: str
    tier: str
    t0_us: float
    t1_us: float | None = None
    tags: dict = dataclasses.field(default_factory=dict)

    @property
    def dur_us(self) -> float:
        return 0.0 if self.t1_us is None else self.t1_us - self.t0_us

    def to_dict(self) -> dict:
        return {"name": self.name, "tier": self.tier,
                "t0_us": self.t0_us, "t1_us": self.t1_us,
                "tags": dict(self.tags)}


@dataclasses.dataclass
class TraceEvent:
    """One overlay interval or zero-duration annotation (retry rungs,
    wire/verify sub-phases) — detail ON the chain, never part of it."""

    name: str
    tier: str
    t0_us: float
    t1_us: float
    tags: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "tier": self.tier,
                "t0_us": self.t0_us, "t1_us": self.t1_us,
                "tags": dict(self.tags)}


class TraceContext:
    """The per-request trace: a gapless span chain plus overlay events.

    Mutated from the scheduler loop that owns the request (``submit``
    runs on a caller thread, but a request enters the step loop only
    through the queue, so chain mutations never race).  Deterministic:
    ids come from a process counter, never randomness.
    """

    __slots__ = ("trace_id", "req_id", "state", "spans", "events",
                 "first_token_us", "dropped", "_wall0_us", "_mono0_ns")

    def __init__(self, req_id: int, tier: str):
        self.trace_id = f"t{int(req_id)}-{next(_ids):04x}"
        self.req_id = int(req_id)
        self.state: str | None = None          # terminal request state
        self.spans: list[Span] = []
        self.events: list[TraceEvent] = []
        self.first_token_us: float | None = None
        self.dropped = 0
        # wall anchor advanced by monotonic deltas: the obs.tracing
        # timebase, so request spans and process spans share one clock
        self._wall0_us = time.time_ns() / 1e3
        self._mono0_ns = time.perf_counter_ns()
        self.begin("queue_wait", tier=tier)

    # -- clock -------------------------------------------------------------

    def now_us(self) -> float:
        return self._wall0_us \
            + (time.perf_counter_ns() - self._mono0_ns) / 1e3

    @property
    def closed(self) -> bool:
        return self.state is not None

    @property
    def t0_us(self) -> float:
        return self.spans[0].t0_us if self.spans else self._wall0_us

    @property
    def total_ms(self) -> float:
        if not self.spans or self.spans[-1].t1_us is None:
            return 0.0
        return (self.spans[-1].t1_us - self.spans[0].t0_us) / 1e3

    # -- the chain ---------------------------------------------------------

    def begin(self, name: str, *, tier: str, **tags) -> None:
        """Close the open span and open ``name`` at the SAME timestamp
        — the gapless-chain contract.  No-op after :meth:`end`."""
        if self.closed:
            return
        now = self.now_us()
        if self.spans and self.spans[-1].t1_us is None:
            self.spans[-1].t1_us = now
        if len(self.spans) >= MAX_SPANS:
            # coalesce: the open span absorbs the hop (chain stays
            # gapless); reopen it and count the drop
            self.dropped += 1
            last = self.spans[-1]
            last.t1_us = None
            last.tags["coalesced"] = last.tags.get("coalesced", 0) + 1
            return
        self.spans.append(Span(name, tier, now, None, dict(tags)))

    def end(self, state: str, *, tier: str | None = None, **tags) -> None:
        """Close the chain at the terminal request state (idempotent)."""
        if self.closed:
            return
        now = self.now_us()
        if self.spans and self.spans[-1].t1_us is None:
            self.spans[-1].t1_us = now
        self.state = str(state)
        if tags and self.spans:
            self.spans[-1].tags.update(tags)
        del tier

    # -- overlays ----------------------------------------------------------

    def annotate(self, name: str, *, tier: str = "", **tags) -> None:
        """Zero-duration annotation at now (admission marks, retry
        rungs, re-prefill decisions — reason strings ride the tags)."""
        if self.closed:
            return
        now = self.now_us()
        self.events.append(TraceEvent(name, tier, now, now, dict(tags)))

    def event(self, name: str, t0_us: float, t1_us: float, *,
              tier: str = "", **tags) -> None:
        """Overlay interval (wire time, stamp-verify time): detail the
        attributor splits exposed-vs-overlapped per phase."""
        self.events.append(
            TraceEvent(name, tier, float(t0_us), float(t1_us), dict(tags)))

    def mark_first_token(self) -> None:
        if self.first_token_us is None:
            self.first_token_us = self.now_us()

    # -- read --------------------------------------------------------------

    def ttft_ms(self) -> float | None:
        if self.first_token_us is None or not self.spans:
            return None
        return (self.first_token_us - self.spans[0].t0_us) / 1e3

    def tiers(self) -> list[str]:
        out: list[str] = []
        for s in self.spans:
            if not out or out[-1] != s.tier:
                out.append(s.tier)
        return out

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "req_id": self.req_id,
            "state": self.state,
            "tiers": self.tiers(),
            "t0_us": self.t0_us,
            "total_ms": self.total_ms,
            "ttft_ms": self.ttft_ms(),
            "dropped_spans": self.dropped,
            "spans": [s.to_dict() for s in self.spans],
            "events": [e.to_dict() for e in self.events],
            "attribution": attribute_request(self) if self.closed else None,
        }


def from_dict(d: dict) -> TraceContext:
    """Rebuild a trace from its :meth:`TraceContext.to_dict` JSON (the
    ``/debug/trace/<id>`` payload / an :func:`export_traces` file) so
    the waterfall and attributor run offline."""
    tr = TraceContext.__new__(TraceContext)
    tr.trace_id = d["trace_id"]
    tr.req_id = int(d.get("req_id", -1))
    tr.state = d.get("state")
    tr.first_token_us = None
    tr.dropped = int(d.get("dropped_spans", 0))
    tr._mono0_ns = time.perf_counter_ns()
    tr.spans = [Span(s["name"], s.get("tier", ""), s["t0_us"],
                     s.get("t1_us"), dict(s.get("tags", {})))
                for s in d.get("spans", [])]
    tr.events = [TraceEvent(e["name"], e.get("tier", ""), e["t0_us"],
                            e["t1_us"], dict(e.get("tags", {})))
                 for e in d.get("events", [])]
    tr._wall0_us = tr.spans[0].t0_us if tr.spans else 0.0
    if d.get("ttft_ms") is not None and tr.spans:
        tr.first_token_us = tr.spans[0].t0_us + d["ttft_ms"] * 1e3
    return tr


# ---------------------------------------------------------------------------
# lifecycle helpers (the serve-layer call sites)


def maybe_begin(req, tier: str):
    """Mint (or resume) the request's trace at ``Scheduler.submit``:
    returns None when the plane is off or this thread is suppressed —
    the serve loop then sees ``req.trace is None`` everywhere and runs
    byte-identical.  A request that already carries a trace (re-prefill
    resubmission on the decode tier) re-enters the queue phase on the
    EXISTING chain instead of minting a second id."""
    tr = getattr(req, "trace", None)
    if tr is not None:
        tr.begin("queue_wait", tier=tier, resubmit=True)
        return tr
    if not enabled():
        return None
    tr = TraceContext(req.req_id, tier)
    req.trace = tr
    return tr


def finish(req) -> None:
    """Close the request's trace at its terminal state and retire it
    into the ring (idempotent; no-op for untraced requests)."""
    tr = getattr(req, "trace", None)
    if tr is None or tr.closed:
        return
    reason = getattr(req, "error", None) or getattr(req, "shed_reason", None)
    state = getattr(getattr(req, "state", None), "value", None) or "done"
    if reason:
        tr.end(state, reason=str(reason))
    else:
        tr.end(state)
    RING.retire(tr)


def reopen_for_failover(req) -> None:
    """Un-close a trace that a replica-local terminal state already
    finished, so a fleet failover resubmission extends the SAME chain
    (``serve.fleet``): ``Scheduler._fail_slot`` ended the chain at the
    failure and retired it, but the request is about to be re-prefilled
    on a survivor — the failed replica's time must stay accounted on
    this request's sketch samples, not restart a fresh clock.  The
    terminal span reopens (its close moves to the resubmit's
    ``queue_wait`` begin, keeping the chain gapless) and the next
    :func:`finish` re-retires under the same trace id, replacing the
    ring entry.  No-op for untraced or still-open requests."""
    tr = getattr(req, "trace", None)
    if tr is None or not tr.closed:
        return
    tr.state = None
    if tr.spans:
        tr.spans[-1].t1_us = None


# ---------------------------------------------------------------------------
# the retained-trace ring


class TraceRing:
    """Bounded ring of the last-N completed traces (``TDT_TRACE_RING``,
    default 256): the exemplar lookups and ``/debug/trace`` resolve
    against it.  Thread-safe; oldest traces evict first."""

    def __init__(self, cap: int | None = None):
        if cap is None:
            raw = os.environ.get("TDT_TRACE_RING", "").strip()
            cap = int(raw) if raw.isdigit() and int(raw) > 0 \
                else DEFAULT_RING
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._traces: OrderedDict[str, TraceContext] = OrderedDict()

    def retire(self, trace: TraceContext) -> None:
        with self._lock:
            self._traces[trace.trace_id] = trace
            self._traces.move_to_end(trace.trace_id)
            while len(self._traces) > self.cap:
                self._traces.popitem(last=False)

    def get(self, trace_id: str) -> TraceContext | None:
        with self._lock:
            return self._traces.get(trace_id)

    def ids(self) -> list[str]:
        """Retained ids, oldest first."""
        with self._lock:
            return list(self._traces)

    def recent(self, n: int = 16) -> list[TraceContext]:
        with self._lock:
            return list(self._traces.values())[-n:]

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


RING = TraceRing()


# ---------------------------------------------------------------------------
# retry-rung plumbing (resilience.policy -> the active trace)

_tls = threading.local()


@contextlib.contextmanager
def activate(trace: TraceContext | None):
    """Bind ``trace`` as this thread's active trace for the enclosed
    call, so ladder rungs recorded deep inside ``resilient_call`` attach
    to the request that paid them (``note_rung``)."""
    prev = getattr(_tls, "active", None)
    _tls.active = trace
    try:
        yield
    finally:
        _tls.active = prev


def note_rung(op: str, kind: str, reason: str) -> None:
    """One failure-ladder rung (retry / fallback) against this thread's
    active trace; reason strings land as span tags.  No-op (one
    thread-local read) when no trace is active."""
    tr = getattr(_tls, "active", None)
    if tr is None:
        return
    tr.annotate(kind, op=op, reason=str(reason)[:240])


# ---------------------------------------------------------------------------
# the SLO attributor


def verify_chain(trace: TraceContext, *, tol_us: float = 0.5) -> list[str]:
    """Gapless-chain check: every hop accounted, contiguous, closed.
    Returns problem strings (empty = clean) — the ``tdt_lint --trace``
    per-request gate."""
    problems: list[str] = []
    if not trace.spans:
        return [f"{trace.trace_id}: no spans recorded"]
    if not trace.closed:
        problems.append(f"{trace.trace_id}: trace never reached a "
                        f"terminal state")
    for a, b in zip(trace.spans, trace.spans[1:]):
        if a.t1_us is None:
            problems.append(
                f"{trace.trace_id}: span {a.name!r} never closed but "
                f"{b.name!r} follows it")
        elif abs(b.t0_us - a.t1_us) > tol_us:
            problems.append(
                f"{trace.trace_id}: {abs(b.t0_us - a.t1_us):.1f}us gap "
                f"between {a.name!r} and {b.name!r} — a hop is "
                f"unaccounted")
    if trace.closed and trace.spans[-1].t1_us is None:
        problems.append(f"{trace.trace_id}: final span "
                        f"{trace.spans[-1].name!r} left open")
    return problems


def attribute_request(trace: TraceContext) -> dict:
    """Decompose the trace into named phase budgets.

    ``phases[p]["exposed_ms"]`` is the chain wall time spent in phase
    ``p`` — the chain partitions [submit, terminal], so the exposed
    sums equal ``e2e_ms`` exactly (``gap_ms`` reports any violation).
    ``overlapped_ms`` is overlay-event time of phase ``p`` that fell
    UNDER another phase's chain time (work hidden behind other hops —
    the ``obs.report`` exposed-vs-hidden interval arithmetic).
    ``ttft_phases`` is the same decomposition clipped to the first
    token.  ``dominant_phase`` names the largest exposed budget — the
    one-line answer to "where did this request's latency go"."""
    from .report import _subtract, _total, _union

    spans = [s for s in trace.spans if s.t1_us is not None]
    if not spans:
        return {"trace_id": trace.trace_id, "e2e_ms": 0.0,
                "gap_ms": 0.0, "phases": {}, "ttft_phases": {},
                "ttft_ms": None, "dominant_phase": None}
    t0 = spans[0].t0_us
    t_end = spans[-1].t1_us
    gap_us = sum(max(0.0, b.t0_us - a.t1_us)
                 for a, b in zip(spans, spans[1:]))

    chain: dict[str, list[tuple[float, float]]] = {}
    counts: dict[str, int] = {}
    for s in spans:
        p = PHASE_OF.get(s.name, s.name)
        chain.setdefault(p, []).append((s.t0_us, s.t1_us))
        counts[p] = counts.get(p, 0) + 1
    overlays: dict[str, list[tuple[float, float]]] = {}
    for e in trace.events:
        if e.t1_us <= e.t0_us:
            continue
        p = EVENT_PHASE_OF.get(e.name)
        if p is not None:
            overlays.setdefault(p, []).append((e.t0_us, e.t1_us))

    phases: dict[str, dict] = {}
    for p, ivs in chain.items():
        exposed_ms = sum(e - b for b, e in ivs) / 1e3
        ov = overlays.get(p, [])
        overlapped_ms = _total(_subtract(_union(ov), _union(ivs))) / 1e3 \
            if ov else 0.0
        phases[p] = {"exposed_ms": exposed_ms,
                     "overlapped_ms": overlapped_ms,
                     "spans": counts[p]}

    ttft_ms = trace.ttft_ms()
    ttft_phases: dict[str, float] = {}
    if ttft_ms is not None:
        cut = trace.first_token_us
        for p, ivs in chain.items():
            ms = sum(min(e, cut) - b for b, e in ivs if b < cut) / 1e3
            if ms > 0:
                ttft_phases[p] = ms
    dominant = max(phases, key=lambda p: phases[p]["exposed_ms"]) \
        if phases else None
    return {
        "trace_id": trace.trace_id,
        "state": trace.state,
        "e2e_ms": (t_end - t0) / 1e3,
        "gap_ms": gap_us / 1e3,
        "ttft_ms": ttft_ms,
        "phases": phases,
        "ttft_phases": ttft_phases,
        "dominant_phase": dominant,
    }


def select_cohort(traces: list, q: float, *,
                  width: float = 0.2) -> list:
    """The closed traces whose end-to-end latency sits in the quantile
    band ``[q - width/2, q + width/2]`` — the cohort-selection half of
    the regression-forensics pairing (``obs.diff.diff_cohorts``): the
    p50 cohort is ``select_cohort(ts, 0.5)``, the p99 exemplars
    ``select_cohort(ts, 0.99, width=0.02)`` (which degenerates to the
    slowest trace(s) of a small ring).  Always returns at least one
    trace when any closed trace exists."""
    closed = [t for t in traces
              if t.spans and t.spans[-1].t1_us is not None]
    if not closed:
        return []
    closed.sort(key=lambda t: t.total_ms)
    n = len(closed)
    lo = max(0, min(n - 1, int((q - width / 2) * n)))
    hi = max(lo + 1, min(n, int((q + width / 2) * n + 1)))
    return closed[lo:hi]


# ---------------------------------------------------------------------------
# export: waterfall text, Chrome trace, JSON dump


def format_waterfall(trace: TraceContext) -> str:
    """The per-request waterfall (``scripts/obs_report.py --request``):
    chain spans with offsets/durations/tiers/tags, overlay events, and
    the attribution footer."""
    att = attribute_request(trace)
    t0 = trace.t0_us
    ttft = "-" if att["ttft_ms"] is None else f"{att['ttft_ms']:.3f}"
    lines = [
        f"trace {trace.trace_id}  request {trace.req_id}  "
        f"state {trace.state or 'open'}  e2e {att['e2e_ms']:.3f} ms  "
        f"ttft {ttft} ms",
    ]
    header = ("offset_ms", "dur_ms", "tier", "span", "tags")
    table = [header]
    for s in trace.spans:
        tags = " ".join(f"{k}={v}" for k, v in sorted(s.tags.items()))
        table.append((f"{(s.t0_us - t0) / 1e3:.3f}",
                      f"{s.dur_us / 1e3:.3f}", s.tier, s.name, tags))
    widths = [max(len(r[i]) for r in table) for i in range(4)]
    for i, row in enumerate(table):
        lines.append("  ".join(
            c.rjust(w) if j < 2 else c.ljust(w)
            for j, (c, w) in enumerate(zip(row[:4], widths)))
            + ("  " + row[4] if row[4] else ""))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    for e in trace.events:
        tags = " ".join(f"{k}={v}" for k, v in sorted(e.tags.items()))
        dur = (e.t1_us - e.t0_us) / 1e3
        lines.append(f"  +{(e.t0_us - t0) / 1e3:.3f}ms "
                     f"{e.name}" + (f" ({dur:.3f}ms)" if dur else "")
                     + (f" {tags}" if tags else ""))
    parts = []
    for p, d in sorted(att["phases"].items(),
                       key=lambda kv: -kv[1]["exposed_ms"]):
        s = f"{p} {d['exposed_ms']:.3f}ms"
        if d["overlapped_ms"]:
            s += f" ({d['overlapped_ms']:.3f}ms overlapped)"
        parts.append(s)
    lines.append(f"attribution: {' | '.join(parts)}  "
                 f"dominant={att['dominant_phase']}  "
                 f"gap={att['gap_ms']:.3f}ms")
    return "\n".join(lines) + "\n"


def to_chrome(traces) -> list[dict]:
    """Chrome-trace events for one or more traces: one pid LANE per
    tier, one tid row per request — the same timebase as
    ``obs.tracing`` spans, so ``tools.trace_merge`` (with its
    ``ts_offsets`` clock-alignment path for cross-process tiers) merges
    request traces and process span traces into one timeline."""
    if isinstance(traces, TraceContext):
        traces = [traces]
    tier_pids: dict[str, int] = {}
    evs: list[dict] = []
    for tr in traces:
        for s in tr.spans:
            pid = tier_pids.setdefault(s.tier, 9000 + len(tier_pids))
            ev = {"name": s.name, "cat": "request", "ph": "X",
                  "ts": s.t0_us, "dur": s.dur_us,
                  "pid": pid, "tid": tr.req_id,
                  "args": {"trace_id": tr.trace_id, **s.tags}}
            evs.append(ev)
        for e in tr.events:
            pid = tier_pids.setdefault(e.tier or "serve",
                                       9000 + len(tier_pids))
            if e.t1_us > e.t0_us:
                evs.append({"name": e.name, "cat": "request", "ph": "X",
                            "ts": e.t0_us, "dur": e.t1_us - e.t0_us,
                            "pid": pid, "tid": tr.req_id,
                            "args": {"trace_id": tr.trace_id, **e.tags}})
            else:
                evs.append({"name": e.name, "cat": "request", "ph": "i",
                            "s": "p", "ts": e.t0_us, "pid": pid,
                            "tid": tr.req_id,
                            "args": {"trace_id": tr.trace_id, **e.tags}})
    return evs


def export_chrome(path: str, traces=None) -> str:
    """Write traces (default: the whole ring) as Chrome-trace JSON in
    the exact envelope layout ``obs.tracing.export`` uses, so
    ``tools.trace_merge.merge_traces`` (native or Python, with
    ``ts_offsets``) accepts it like any per-process span file."""
    if traces is None:
        traces = RING.recent(len(RING))
    with open(path, "w") as f:
        f.write('{"displayTimeUnit":"ms","traceEvents":')
        f.write(json.dumps(to_chrome(traces), separators=(",", ":")))
        f.write("}")
    return path


def export_traces(path: str, traces=None) -> str:
    """JSON dump of traces (default: the ring) for offline waterfall /
    attribution (``obs_report.py --request <id> --trace-file dump``)."""
    if traces is None:
        traces = RING.recent(len(RING))
    with open(path, "w") as f:
        json.dump({"traces": [tr.to_dict() for tr in traces]}, f,
                  indent=1, sort_keys=True)
    return path


def load_traces(path: str) -> list[TraceContext]:
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "traces" in obj:
        return [from_dict(d) for d in obj["traces"]]
    if isinstance(obj, dict):
        return [from_dict(obj)]
    return [from_dict(d) for d in obj]
