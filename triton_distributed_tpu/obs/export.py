"""Metric exporters: JSONL append, Prometheus text format, summary table.

All three render :meth:`obs.registry.Registry.snapshot` rows; none hold
references into the registry, so exporting is safe while hot paths keep
recording.
"""

from __future__ import annotations

import json
import math
import time

from .registry import bucket_quantile


def _num(v) -> str:
    """Exact float text for exposition values: ``repr`` round-trips every
    float (what prometheus_client emits), where ``%g``'s 6 significant
    digits would silently truncate large byte counters."""
    return repr(float(v))


def write_jsonl(registry, path: str, *, extra: dict | None = None) -> int:
    """Append one JSON line per metric to ``path``; returns the number of
    lines written.  Every line carries the same ``ts`` (seconds since
    epoch) so one append is one identifiable snapshot; ``extra`` keys
    (run id, step, host) are merged into every line."""
    rows = registry.snapshot()
    ts = time.time()
    with open(path, "a") as f:
        for row in rows:
            rec = {"ts": ts, **(extra or {}), **row}
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")
    return len(rows)


def read_jsonl(path: str) -> list[dict]:
    """Parse a :func:`write_jsonl` file back into rows (all snapshots,
    oldest first) — the round-trip half used by tests and the report
    tooling."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_labels(labels: dict, extra: dict | None = None) -> str:
    items = {**labels, **(extra or {})}
    if not items:
        return ""
    body = ",".join(
        f'{_prom_name(str(k))}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(items.items())
    )
    return "{" + body + "}"


def to_prometheus(registry) -> str:
    """Prometheus text exposition (v0.0.4) of the registry: counters as
    ``<name>_total``, histograms as cumulative ``_bucket{le=...}`` series
    plus ``_sum``/``_count`` — scrapeable by a stock Prometheus or
    inspectable with grep."""
    lines: list[str] = []
    seen_types: set[tuple[str, str]] = set()
    for row in registry.snapshot():
        kind, labels = row["kind"], row["labels"]
        if kind == "counter":
            name = _prom_name(row["name"]) + "_total"
            if (name, "counter") not in seen_types:
                seen_types.add((name, "counter"))
                lines.append(f"# TYPE {name} counter")
            lines.append(f"{name}{_prom_labels(labels)} {_num(row['value'])}")
        elif kind == "gauge":
            name = _prom_name(row["name"])
            if (name, "gauge") not in seen_types:
                seen_types.add((name, "gauge"))
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{_prom_labels(labels)} {_num(row['value'])}")
        elif kind == "histogram":
            name = _prom_name(row["name"])
            if (name, "histogram") not in seen_types:
                seen_types.add((name, "histogram"))
                lines.append(f"# TYPE {name} histogram")
            for bound, cnt in zip(row["buckets"], row["counts"]):
                lines.append(
                    f"{name}_bucket{_prom_labels(labels, {'le': f'{bound:g}'})}"
                    f" {cnt}"
                )
            lines.append(
                f"{name}_bucket{_prom_labels(labels, {'le': '+Inf'})}"
                f" {row['count']}"
            )
            lines.append(f"{name}_sum{_prom_labels(labels)} {_num(row['sum'])}")
            lines.append(f"{name}_count{_prom_labels(labels)} {row['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus(text: str) -> dict[str, float]:
    """Minimal parser for :func:`to_prometheus` output: maps
    ``name{labels}`` -> value.  For round-trip tests and quick asserts,
    not a general scrape client."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        out[series] = float(value)
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != v or v in (math.inf, -math.inf):
            return "-"
        return f"{v:.4g}"
    return str(v)


def summary_table(registry) -> str:
    """Human-readable aligned table of every metric — the operator view
    (``TDT_OBS=1 python ... ; print(obs.summary())``)."""
    rows = registry.snapshot()
    if not rows:
        return "(no metrics recorded)\n"
    table = [("metric", "labels", "kind", "value / mean", "count",
              "p50", "p99", "max")]
    for row in rows:
        labels = ",".join(f"{k}={v}" for k, v in sorted(row["labels"].items()))
        if row["kind"] == "histogram":
            cnt = row["count"]
            mean = row["sum"] / cnt if cnt else 0.0
            p50 = _quantile_from_row(row, 0.5)
            p99 = _quantile_from_row(row, 0.99)
            table.append((row["name"], labels, "hist", _fmt(mean),
                          str(cnt), _fmt(p50), _fmt(p99), _fmt(row["max"])))
        else:
            table.append((row["name"], labels, row["kind"],
                          _fmt(row["value"]), "-", "-", "-", "-"))
    widths = [max(len(r[i]) for r in table) for i in range(len(table[0]))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines) + "\n"


def _quantile_from_row(row: dict, q: float):
    return bucket_quantile(row["buckets"], row["counts"], row["count"],
                           row["max"], q)
