"""Live serving telemetry: streaming quantile sketches and windowed rates.

The registry's fixed-bucket histograms (``obs.registry``) are built for
cross-process mergeability, which pins their boundaries at creation — a
p99 read off them is quantized to the nearest bucket bound (up to 2.5x
off between the coarse decade bounds).  A live serving plane needs
**streaming percentiles with a bounded relative error** and **windowed
rates** ("tokens/s over the last minute", not "since process start").
This module is that layer, still zero-dep and thread-safe:

- :class:`QuantileSketch` — a DDSketch-style log-bucket histogram with a
  fixed gamma: bucket ``i`` covers ``(gamma^(i-1), gamma^i]``, so any
  quantile estimate is within ``alpha`` RELATIVE error of the true value
  (``gamma = (1+alpha)/(1-alpha)``; default alpha = 1%).  Unlike a real
  DDSketch there is no bucket collapsing by default — serving latencies
  span ~6 decades, which at 1% is < 700 live buckets; an explicit
  ``max_buckets`` collapses the smallest keys if a pathological feed
  grows past it.
- :class:`WindowedRate` — per-second event/value buckets over a sliding
  window (default 60 s): ``rate()`` is the windowed mean per second,
  ``total`` the lifetime sum.
- :class:`ServeStats` — the process-global collector the engine and the
  comm entry points feed (request/prefill/decode latency sketches,
  tokens/s and request/s windows, queue depth, KV/device-memory
  occupancy, per-collective wire-byte rates).  Snapshotted into
  ``Engine.health()`` and rendered by ``obs.server``'s ``/metrics``.

Everything rides the same ``TDT_OBS=1`` gate as the registry: the feed
helpers no-op when ``obs.enabled()`` is false, so the serve loop is
unchanged with telemetry off.  Accuracy bound pinned by
``tests/test_obs.py::test_sketch_quantile_error_bound``.
"""

from __future__ import annotations

import math
import threading
import time

DEFAULT_ALPHA = 0.01          # 1% relative quantile error
DEFAULT_WINDOW_S = 60.0       # rate window
SERVE_QUANTILES = (0.5, 0.9, 0.99)


class QuantileSketch:
    """Fixed-gamma log-bucket quantile sketch (DDSketch family).

    ``observe(v)`` maps ``v > 0`` to key ``ceil(log_gamma(v))``;
    ``quantile(q)`` walks the sorted keys to the q-rank bucket and
    returns its midpoint ``2 * gamma^k / (gamma + 1)`` — within
    ``alpha`` relative error of the true quantile by construction.
    Non-positive observations land in a dedicated zero bucket (rank 0
    side).  Thread-safe; ``merge`` adds another sketch of the SAME gamma.

    **Exemplar slots** (ISSUE 14): ``observe(v, exemplar="t42-001a")``
    additionally remembers the LAST exemplar id per bucket (one string
    per live bucket — bounded by the bucket cap), and ``exemplar(q)``
    returns the id stored in the q-rank bucket: "show me a p99 request"
    resolves to a retained trace id in one call.  Omitting the exemplar
    argument keeps the sketch byte-identical to the pre-exemplar shape.
    """

    __slots__ = ("alpha", "gamma", "_lg", "max_buckets", "_lock",
                 "_buckets", "_zero", "_count", "_sum", "_min", "_max",
                 "_exemplars", "_zero_exemplar")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_buckets: int = 4096):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha {alpha} outside (0, 1)")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self.gamma)
        self.max_buckets = int(max_buckets)
        self._lock = threading.Lock()
        self._buckets: dict[int, int] = {}
        self._zero = 0
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._exemplars: dict[int, str] = {}
        self._zero_exemplar: str | None = None

    def _key(self, v: float) -> int:
        return math.ceil(math.log(v) / self._lg)

    def observe(self, v: float, exemplar: str | None = None) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if v <= 0.0:
                self._zero += 1
                if exemplar is not None:
                    self._zero_exemplar = exemplar
                return
            k = self._key(v)
            self._buckets[k] = self._buckets.get(k, 0) + 1
            if exemplar is not None:
                self._exemplars[k] = exemplar
            if len(self._buckets) > self.max_buckets:
                # collapse the two smallest keys (lowest-latency tail):
                # high quantiles — the serving signal — stay exact-bound
                ks = sorted(self._buckets)
                self._buckets[ks[1]] = (self._buckets.pop(ks[0])
                                        + self._buckets[ks[1]])
                ex = self._exemplars.pop(ks[0], None)
                if ex is not None:
                    self._exemplars.setdefault(ks[1], ex)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def quantile(self, q: float) -> float:
        """q-quantile estimate (0.0 when empty); relative error <= alpha
        for positive observations, with the extremes (q == 0 / q == 1)
        reported EXACTLY from the tracked min/max."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            return self.quantile_unlocked(q)

    def merge(self, other: "QuantileSketch") -> None:
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError("cannot merge sketches with different gamma")
        with other._lock:
            buckets = dict(other._buckets)
            zero, count, s = other._zero, other._count, other._sum
            mn, mx = other._min, other._max
            exemplars = dict(other._exemplars)
            zex = other._zero_exemplar
        with self._lock:
            for k, c in buckets.items():
                self._buckets[k] = self._buckets.get(k, 0) + c
            self._zero += zero
            self._count += count
            self._sum += s
            self._min = min(self._min, mn)
            self._max = max(self._max, mx)
            self._exemplars.update(exemplars)
            if zex is not None:
                self._zero_exemplar = zex

    def exemplar(self, q: float) -> str | None:
        """The exemplar id stored in the q-rank bucket (None when that
        bucket never saw one — e.g. traffic recorded with the trace
        plane off, or under ``obs.suppress()``)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            return self._exemplar_unlocked(q)

    def _exemplar_unlocked(self, q: float) -> str | None:
        # the same bucket walk as quantile_unlocked, lock held by caller
        if not self._count:
            return None
        rank = q * (self._count - 1)
        seen = self._zero
        if rank < seen:
            return self._zero_exemplar
        for k in sorted(self._buckets):
            seen += self._buckets[k]
            if rank < seen:
                return self._exemplars.get(k)
        return None

    def to_dict(self) -> dict:
        with self._lock:
            out = {
                "alpha": self.alpha, "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "quantiles": {f"p{int(q * 100)}": self.quantile_unlocked(q)
                              for q in SERVE_QUANTILES},
            }
            if self._exemplars or self._zero_exemplar:
                # additive (only when the feed attached trace ids), and
                # computed under the SAME lock hold as the quantiles so
                # the id next to a p99 value belongs to the same state
                out["exemplars"] = {
                    f"p{int(q * 100)}": self._exemplar_unlocked(q)
                    for q in SERVE_QUANTILES
                }
        return out

    def quantile_unlocked(self, q: float) -> float:
        # the walk itself, lock held by the caller (quantile / to_dict)
        if not self._count:
            return 0.0
        if q <= 0.0:
            return self._min
        if q >= 1.0:
            return self._max
        rank = q * (self._count - 1)
        seen = self._zero
        if rank < seen:
            return min(self._min, 0.0)
        for k in sorted(self._buckets):
            seen += self._buckets[k]
            if rank < seen:
                # bucket midpoint: within alpha of anything inside
                return 2.0 * self.gamma ** k / (self.gamma + 1.0)
        return self._max


class WindowedRate:
    """Sliding-window rate: per-second value buckets over ``window_s``.

    ``add(v)`` accumulates into the current second's bucket; ``rate()``
    is the window sum divided by the window length (units/s), so a burst
    decays out of the reading within one window.  ``total`` is the
    lifetime sum (a counter).  Thread-safe.
    """

    __slots__ = ("window_s", "_lock", "_buckets", "_total")

    def __init__(self, window_s: float = DEFAULT_WINDOW_S):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._buckets: dict[int, float] = {}
        self._total = 0.0

    def _prune(self, now: float) -> None:
        floor = int(now - self.window_s)
        if len(self._buckets) > self.window_s + 2:
            for s in [s for s in self._buckets if s < floor]:
                del self._buckets[s]

    def add(self, v: float = 1.0, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        s = int(now)
        with self._lock:
            self._buckets[s] = self._buckets.get(s, 0.0) + v
            self._total += v
            self._prune(now)

    @property
    def total(self) -> float:
        return self._total

    def rate(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        floor = now - self.window_s
        with self._lock:
            live = sum(v for s, v in self._buckets.items() if s >= floor)
        return live / self.window_s


class ServeStats:
    """The live serving collector (one per process, ``STATS`` below).

    Fed by ``models/engine.py`` (request begin/end, per-request latency
    stats, occupancy) and ``obs.record_collective`` (wire bytes); every
    feed helper is cheap and lock-scoped per metric.  ``snapshot()`` is
    the JSON the engine's ``health()`` embeds; ``to_prometheus()`` the
    text block ``obs.server`` appends to ``/metrics``.
    """

    def __init__(self, *, alpha: float = DEFAULT_ALPHA,
                 window_s: float = DEFAULT_WINDOW_S):
        self._lock = threading.Lock()
        self._alpha = alpha
        self._window_s = window_s
        self.request_ms = QuantileSketch(alpha)
        self.prefill_ms = QuantileSketch(alpha)
        self.decode_ms_per_token = QuantileSketch(alpha)
        # scheduler-plane SLO sketches (ISSUE 6): time-to-first-token
        # measured submit -> first sampled token (queue wait included —
        # that IS the saturation signal)
        self.ttft_ms = QuantileSketch(alpha)
        self.tokens = WindowedRate(window_s)
        self.requests = WindowedRate(window_s)
        self.failed_requests = WindowedRate(window_s)
        # overload-behavior counters: sheds (admission rejected),
        # preemptions (pages evicted, request parked + recomputed)
        self.sheds = WindowedRate(window_s)
        self.preemptions = WindowedRate(window_s)
        self.evicted_pages = WindowedRate(window_s)
        # disaggregated-handoff plane (serve.handoff): per-transfer
        # latency sketch + pages-shipped window — the `handoff_ms_p99`
        # / `handoff_pages_per_s` SLO surface
        self.handoff_ms = QuantileSketch(alpha)
        self.handoff_pages = WindowedRate(window_s)
        self._wire: dict[str, WindowedRate] = {}
        self._queue_depth = 0
        self._gauges: dict[str, float] = {}

    # -- feed (call sites gate on obs.enabled()) ---------------------------

    def request_begin(self) -> None:
        with self._lock:
            self._queue_depth += 1

    def request_end(self, *, failed: bool = False) -> None:
        with self._lock:
            self._queue_depth = max(0, self._queue_depth - 1)
        self.requests.add(1.0)
        if failed:
            self.failed_requests.add(1.0)

    def observe_request(self, *, prompt_len: int, gen_len: int,
                        stats: dict, batch: int = 1) -> None:
        """One completed ``Engine.serve`` request (its stats dict).
        ``batch`` scales the token window: a B=128 request produces
        ``B * gen_len`` tokens, matching the registry's
        ``engine_tokens_generated`` accounting."""
        decode_steps = max(gen_len - 1, 1)
        prefill = float(stats.get("prefill_ms", 0.0))
        per_tok = float(stats.get("decode_ms_per_token", 0.0))
        self.prefill_ms.observe(prefill)
        self.decode_ms_per_token.observe(per_tok)
        self.request_ms.observe(prefill + per_tok * decode_steps)
        self.tokens.add(float(gen_len) * max(int(batch), 1))

    # -- scheduler feeds (serve.Scheduler; gated on obs.enabled() there) ---

    def observe_ttft(self, ms: float,
                     exemplar: str | None = None) -> None:
        """``exemplar``: the request's trace id (TDT_TRACE=1 only) —
        the p99 bucket then answers "show me a p99 request" with a
        retained trace id (``obs.request_trace``)."""
        self.ttft_ms.observe(float(ms), exemplar)

    def request_completed(self, e2e_ms: float, *, tokens: int = 0,
                          exemplar: str | None = None) -> None:
        """One scheduler-completed request: end-to-end latency (submit
        -> last token) into the request sketch; the per-step token feed
        happens at decode time, not here."""
        self.request_ms.observe(float(e2e_ms), exemplar)
        self.requests.add(1.0)
        del tokens   # tokens ride the per-step feed; kept for call shape

    def request_failed(self) -> None:
        self.requests.add(1.0)
        self.failed_requests.add(1.0)

    def request_shed(self) -> None:
        self.sheds.add(1.0)

    def request_preempted(self, *, pages: int = 0) -> None:
        self.preemptions.add(1.0)
        if pages:
            self.evicted_pages.add(float(pages))

    def observe_handoff(self, ms: float, *, pages: int = 0) -> None:
        """One completed KV-handoff transfer (serve.handoff): wire
        latency into the sketch, shipped pages into the rate window."""
        self.handoff_ms.observe(float(ms))
        if pages:
            self.handoff_pages.add(float(pages))

    def observe_collective(self, op: str, *, wire_bytes: float) -> None:
        r = self._wire.get(op)
        if r is None:
            with self._lock:
                r = self._wire.setdefault(op, WindowedRate(self._window_s))
        r.add(float(wire_bytes))

    def set_gauge(self, name: str, value: float) -> None:
        """Occupancy-style last-write-wins values (kv_cache_seq_occupancy,
        device_memory_occupancy)."""
        with self._lock:
            self._gauges[name] = float(value)

    @property
    def queue_depth(self) -> int:
        return self._queue_depth

    # -- read --------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            gauges = dict(self._gauges)
            depth = self._queue_depth
            wire = dict(self._wire)
        return {
            "queue_depth": depth,
            "request_ms": self.request_ms.to_dict(),
            "prefill_ms": self.prefill_ms.to_dict(),
            "decode_ms_per_token": self.decode_ms_per_token.to_dict(),
            "ttft_ms": self.ttft_ms.to_dict(),
            "handoff_ms": self.handoff_ms.to_dict(),
            "handoff_pages_per_s_window": self.handoff_pages.rate(),
            "handoff_pages_total": self.handoff_pages.total,
            "tokens_per_s_window": self.tokens.rate(),
            "requests_per_s_window": self.requests.rate(),
            "failed_requests_per_s_window": self.failed_requests.rate(),
            "sheds_per_s_window": self.sheds.rate(),
            "preemptions_per_s_window": self.preemptions.rate(),
            "tokens_total": self.tokens.total,
            "requests_total": self.requests.total,
            "sheds_total": self.sheds.total,
            "preemptions_total": self.preemptions.total,
            "evicted_pages_total": self.evicted_pages.total,
            "wire_bytes_per_s_window": {
                op: r.rate() for op, r in sorted(wire.items())
            },
            "gauges": gauges,
        }

    def to_prometheus(self) -> str:
        """Prometheus text block for the live stats — summary-style
        quantile series for the sketches, gauges for windows/occupancy.
        Appended after the registry exposition by ``obs.server``."""
        lines: list[str] = []

        def sk(name: str, sketch: QuantileSketch) -> None:
            lines.append(f"# TYPE {name} summary")
            for q in SERVE_QUANTILES:
                lines.append(
                    f'{name}{{quantile="{q:g}"}} {sketch.quantile(q)!r}')
            lines.append(f"{name}_sum {sketch.sum!r}")
            lines.append(f"{name}_count {sketch.count}")

        sk("serve_request_ms", self.request_ms)
        sk("serve_prefill_ms", self.prefill_ms)
        sk("serve_decode_ms_per_token", self.decode_ms_per_token)
        sk("serve_ttft_ms", self.ttft_ms)
        sk("serve_handoff_ms", self.handoff_ms)

        def g(name: str, v: float) -> None:
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(v)!r}")

        g("serve_queue_depth", self._queue_depth)
        g("serve_tokens_per_s_window", self.tokens.rate())
        g("serve_requests_per_s_window", self.requests.rate())
        g("serve_failed_requests_per_s_window", self.failed_requests.rate())
        g("serve_sheds_per_s_window", self.sheds.rate())
        g("serve_preemptions_per_s_window", self.preemptions.rate())
        g("serve_sheds_total", self.sheds.total)
        g("serve_preemptions_total", self.preemptions.total)
        g("serve_evicted_pages_total", self.evicted_pages.total)
        g("serve_handoff_pages_per_s_window", self.handoff_pages.rate())
        g("serve_handoff_pages_total", self.handoff_pages.total)
        with self._lock:
            wire = dict(self._wire)
            gauges = dict(self._gauges)
        if wire:
            lines.append("# TYPE serve_wire_bytes_per_s_window gauge")
            for op, r in sorted(wire.items()):
                lines.append(
                    f'serve_wire_bytes_per_s_window{{op="{op}"}} '
                    f"{r.rate()!r}")
        for name, v in sorted(gauges.items()):
            g(f"serve_{name}", v)
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Fresh collector state (tests)."""
        self.__init__(alpha=self._alpha, window_s=self._window_s)


# the process-global collector the engine and comm entry points feed
STATS = ServeStats()
