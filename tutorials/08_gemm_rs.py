"""Tutorial 08 — fused GEMM-ReduceScatter (reference
08-overlapping-gemm-reduce-scatter.rst): compute-ahead-of-wire ring; the
matmul of ring step s hides the transfer of step s-1.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.ops import gemm_rs


def main():
    n, m, k, nn = 8, 256, 512, 256
    mesh = mesh_lib.tp_mesh(n)
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(1), (k, nn), jnp.float32) * 0.1
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))    # K-shard
    b_s = jax.device_put(b, NamedSharding(mesh, P("tp", None)))    # row-shard
    out = gemm_rs(a_s, b_s, mesh)
    want = np.asarray(a @ b)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                               atol=1e-3, rtol=1e-3)
    print("fused GEMM-RS OK:", out.shape)


if __name__ == "__main__":
    main()
