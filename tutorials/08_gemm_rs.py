"""Tutorial 08 — fused GEMM-ReduceScatter (reference
08-overlapping-gemm-reduce-scatter.rst).

The row-parallel half of a TP layer: ``a`` arrives K-sharded (each rank
holds the full M rows of a (M, K/n) slice), ``b`` is row-sharded to
match, and every rank's local matmul produces a PARTIAL (M, N) result
that must be summed over ranks and scattered so rank r keeps rows
[r*M/n, (r+1)*M/n).  Unfused, that is ``matmul`` then ``psum_scatter``
— compute, THEN wire, serially.

The fused op (``ops/gemm_rs.py``) rides a ring instead.  The key idea —
COMPUTE AHEAD OF WIRE — is a scheduling statement:

    at ring step s, compute exactly the output CHUNK whose partial sum
    must depart this step; send it; the next step's chunk matmul runs
    while those bytes fly.

Chunk order falls out of the ring: the partial destined for rank r must
visit every other rank once, so it ORIGINATES at rank r+1 and hops right
n-1 times; each host adds its own contribution for that chunk on
arrival.  On rank ``me`` that means: originate chunk (me-1) mod n, then
at step s receive the partial for chunk (me-s-1) mod n, add my matmul of
that chunk, forward.  After n-1 steps the partial arriving is chunk
``me`` — fully reduced, mine to keep.  Wire per rank: (n-1)/n * M*N
bytes — identical to unfused psum_scatter — but hidden behind n-1 chunk
matmuls.

Below you will:

1. build that schedule inline from XLA pieces (``shard_map`` +
   ``ppermute``) — the algorithm without the Pallas overlap machinery —
   and check it against the plain matmul golden;
2. run the production fused kernel and check the identical result and
   layout;
3. differentiate THROUGH the fused op and see the AG<->RS adjoint
   duality: the backward of a GEMM-RS is built from an AllGather of the
   cotangent (tutorial 07's wire pattern), so the backward pass overlaps
   its communication exactly like the forward.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import functools

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core import compilation
from triton_distributed_tpu.ops import gemm_rs


def ring_gemm_rs_reference(a_loc, b_loc, *, axis: str, n: int):
    """The fused kernel's ring schedule, written as n-1 explicit XLA
    steps inside ``shard_map``.  XLA executes these serially — that is
    the point: the Pallas kernel exists to overlap step s's wire with
    step s+1's matmul — but the chunk order, partial-sum dataflow, and
    final layout are exactly the fused op's (``ops/gemm_rs.py``)."""
    me = jax.lax.axis_index(axis)
    rows = a_loc.shape[0] // n

    def chunk(idx):
        # my contribution to output rows [idx*rows, (idx+1)*rows)
        return jax.lax.dynamic_slice_in_dim(a_loc, idx * rows, rows, 0) @ b_loc

    # originate the partial destined for my LEFT neighbor: it has the
    # longest journey (n-1 hops rightward back around to rank me-1)
    acc = chunk(jax.lax.rem(me + jnp.int32(n - 1), jnp.int32(n)))
    for s in range(1, n):
        # the in-flight partial moves one hop right...
        acc = jax.lax.ppermute(
            acc, axis, [(r, (r + 1) % n) for r in range(n)]
        )
        # ...and I add my matmul for the chunk it now represents; in the
        # fused kernel THIS matmul is what hides the hop's wire time
        acc = acc + chunk(jax.lax.rem(me + jnp.int32(n - s - 1),
                                      jnp.int32(n)))
    return acc  # step n-1 added chunk ``me``: fully reduced, mine


def main():
    n, m, k, nn = 8, 256, 512, 256
    mesh = mesh_lib.tp_mesh(n)
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(1), (k, nn), jnp.float32) * 0.1
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))    # K-shard
    b_s = jax.device_put(b, NamedSharding(mesh, P("tp", None)))    # row-shard
    want = np.asarray(a @ b)

    # 1. the inline XLA ring: same schedule, no overlap machinery
    ref = compilation.jit_shard_map(
        functools.partial(ring_gemm_rs_reference, axis="tp", n=n),
        mesh, in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None),
    )
    got_ref = np.asarray(jax.device_get(ref(a_s, b_s)))
    np.testing.assert_allclose(got_ref, want, atol=1e-3, rtol=1e-3)
    print("inline ppermute ring schedule == a @ b                OK")

    # 2. the production fused kernel: identical values and M-sharded layout
    out = gemm_rs(a_s, b_s, mesh)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                               atol=1e-3, rtol=1e-3)
    print(f"fused gemm_rs == a @ b (M-sharded, global {out.shape}) OK")

    # 3. gradients THROUGH the fused op, vs the dense matmul's gradient
    def loss_fused(a_, b_):
        return (gemm_rs(a_, b_, mesh).astype(jnp.float32) ** 2).sum()

    def loss_dense(a_, b_):
        return ((a_ @ b_) ** 2).sum()

    ga_f, gb_f = jax.grad(loss_fused, argnums=(0, 1))(a_s, b_s)
    ga_d, gb_d = jax.grad(loss_dense, argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(ga_f)),
                               np.asarray(ga_d), atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(jax.device_get(gb_f)),
                               np.asarray(gb_d), atol=2e-2, rtol=2e-2)
    print("grad through fused gemm_rs == dense matmul grad       OK")
    print("\nNext: 09 applies the same overlap discipline to attention "
          "(ring SP).  The reference is inference-only — the VJP checked "
          "here is what lets the training step (12) jit end to end.")


if __name__ == "__main__":
    main()
