"""Tutorial 07 — fused AllGather-GEMM, the framework's thesis op.

Reference: 07-overlapping-allgather-gemm.rst — the canonical
compute-communication-overlap kernel (``allgather_gemm.py``): a producer
moves activation chunks between ranks while a consumer GEMM eats them in
ARRIVAL ORDER, so the wire hides behind the MXU.

The TP problem.  A column-parallel layer computes ``C = AllGather(A) @
B_local``: every rank holds M/n rows of A and N/n columns of B, and needs
ALL of A to produce its column block.  Unfused, that is two serial steps —
wait for the whole AllGather, then matmul:

    t_unfused ~= t_wire + t_mxu

The fused kernel (``ops/ag_gemm.py``) interleaves them at CHUNK
granularity.  Per ring step: forward the chunk that just arrived to the
next rank (async remote DMA), and — while the wire moves it — run the MXU
over the chunk that is already resident.  Compute of step s hides the
wire of step s+1:

    t_fused ~= max(t_wire, t_mxu) + one_chunk_latency

Three design points to read in ``ops/ag_gemm.py`` afterwards:

- **Arrival order is consumption order** (the reference's rank-swizzled
  tile schedule, ``allgather_gemm.py:205-215``): the matmul loop starts
  with the LOCAL chunk (always resident) and then follows the ring, so
  no step ever stalls on data that could not have arrived yet.
- **Per-chunk semaphores, no global barrier**: each forwarded chunk's
  DMA completion semaphore gates exactly the matmul pass that consumes
  it (tutorial 01's rule 2 at production scale).
- **Bidirectional ring** (``bidir=True``, default at n >= 3): chunks
  flow both ways around the ICI ring, halving the longest path.

Below: correctness vs the unfused golden, the autodiff story (the fused
op carries a custom VJP — its backward runs the ADJOINT fused collective,
GEMM-ReduceScatter), and a wall-clock comparison harness that shows the
overlap on a real slice (on the simulated CPU mesh, interpret-mode timing
is meaningless — the harness prints the speed-of-light wire/compute
bounds instead).
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.platform import on_cpu
from triton_distributed_tpu.ops import ag_gemm
from triton_distributed_tpu.tools import perf_model


def main():
    n, m, k, nn = 8, 256, 256, 1024
    mesh = mesh_lib.tp_mesh(n)
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(1), (k, nn), jnp.float32) * 0.1
    # the TP layout: A row-sharded (activations), B column-sharded (weight)
    a_s = jax.device_put(a, NamedSharding(mesh, P("tp", None)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))

    # -- 1. correctness: the fused op == gather-then-matmul ---------------
    out = ag_gemm(a_s, b_s, mesh)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(a @ b), atol=1e-3, rtol=1e-3)
    print(f"1. fused AG-GEMM == AllGather(A) @ B   OK  {out.shape}")

    # -- 2. it differentiates: the backward is the ADJOINT overlap --------
    # d/dA of (AllGather(A) @ B) needs a ReduceScatter of (dC @ B^T) — the
    # mirror-image fused op.  The custom VJP runs it overlapped too, so a
    # training step pays hidden wire in BOTH directions.
    def loss(a_, b_):
        y = ag_gemm(a_, b_, mesh)
        return jnp.mean(jnp.square(y))

    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    da_ref, db_ref = jax.grad(
        lambda a_, b_: jnp.mean(jnp.square(a_ @ b_)), argnums=(0, 1)
    )(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-4, rtol=1e-3)
    print("2. custom VJP (adjoint = fused GEMM-RS) OK")

    # -- 3. the overlap, quantified ---------------------------------------
    # Speed-of-light model: a perfect fusion costs max(wire, compute), an
    # unfused pipeline costs their sum (tools/perf_model.py — the
    # reference's gemm_perf_model.py:232 analogue).
    dtype_bytes = jnp.dtype(a.dtype).itemsize
    t_gemm = perf_model.gemm_sol_ms(m, nn // n, k, a.dtype)
    t_wire = perf_model.allgather_sol_ms((m // n) * k * dtype_bytes, n)
    print(f"3. SOL model at this shape: compute {t_gemm * 1e3:.1f} us, "
          f"wire {t_wire * 1e3:.1f} us -> fused bound "
          f"{max(t_gemm, t_wire) * 1e3:.1f} us vs unfused "
          f"{(t_gemm + t_wire) * 1e3:.1f} us "
          f"({(t_gemm + t_wire) / max(t_gemm, t_wire):.2f}x headroom)")

    if on_cpu():
        print("   (simulated mesh: interpret-mode wall clock is not "
              "meaningful — run this file on a TPU slice, or see "
              "bench.py / docs/perf.md for measured single-chip numbers)")
        return

    # real hardware: interleaved wall-clock comparison vs the unfused path
    from triton_distributed_tpu.core.utils import (
        interleaved_slope_samples, sync,
    )

    @jax.jit
    def unfused(a_, b_):
        ag = jax.lax.with_sharding_constraint(
            a_, NamedSharding(mesh, P(None, None))
        )
        return jnp.matmul(ag, b_)

    fused = jax.jit(lambda a_, b_: ag_gemm(a_, b_, mesh))
    sync(fused(a_s, b_s))
    sync(unfused(a_s, b_s))
    raw = interleaved_slope_samples(
        {"fused": lambda: fused(a_s, b_s),
         "unfused": lambda: unfused(a_s, b_s)}, iters=16, rounds=7,
    )
    def med(xs):
        xs = sorted(x for x in xs if x > 0)   # drop noise-swamped rounds
        return xs[len(xs) // 2] if xs else float("nan")

    t_f, t_u = med(raw["fused"]), med(raw["unfused"])
    print(f"   measured: fused {t_f * 1e6:.0f} us vs unfused "
          f"{t_u * 1e6:.0f} us ({t_u / t_f:.2f}x)")


if __name__ == "__main__":
    main()
