"""Tutorial 07 — fused AllGather-GEMM (reference
07-overlapping-allgather-gemm.rst): the consumer matmul eats gathered
chunks in ring-arrival order, hiding the wire behind the MXU.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.ops import ag_gemm


def main():
    n, m, k, nn = 8, 256, 256, 1024
    mesh = mesh_lib.tp_mesh(n)
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(1), (k, nn), jnp.float32) * 0.1
    a_s = jax.device_put(a, NamedSharding(mesh, P("tp", None)))    # M-shard
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))    # col-shard
    out = ag_gemm(a_s, b_s, mesh)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(a @ b), atol=1e-3, rtol=1e-3)
    print("fused AG-GEMM OK:", out.shape)


if __name__ == "__main__":
    main()
