"""Shared tutorial bootstrap: a virtual 8-device CPU mesh (every tutorial
runs on a laptop; on a real TPU slice delete the force_cpu call and the
same code runs over ICI).  Reference tutorials require N GPUs + torchrun;
here the mesh is simulated (SURVEY.md section 4)."""

from triton_distributed_tpu.core.platform import force_cpu, SPARE_VIRTUAL_DEVICES

MESH_DEVICES = 8


def bootstrap():
    # spares keep interpret-mode kernels deadlock-free at full occupancy
    force_cpu(MESH_DEVICES + SPARE_VIRTUAL_DEVICES)
    import jax

    from triton_distributed_tpu.core import mesh as mesh_lib

    return jax, mesh_lib
