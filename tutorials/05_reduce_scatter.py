"""Tutorial 05 — ring ReduceScatter (reference 05/06-reduce-scatter.rst).

ReduceScatter is AllGather's adjoint: where tutorial 02's ring FORWARDS
chunks unchanged, this ring ADDS into the chunk as it passes.  Every
rank holds stacked (M, R) partial addends; rank r must end with
row-chunk r of the element-wise SUM.  The partial destined for rank r
originates at rank r+1, hops right n-1 times, and each host folds in
its own rows for that chunk — one add per hop, so the reduction is
complete exactly when the partial reaches its owner.

You will write that kernel inline below.  It differs from the
production ``comm/reduce_scatter.py`` in what it leaves out, and the
missing pieces are the production lessons:

* **Buffer reuse needs flow control.**  The inline kernel spends one
  receive slot PER STEP, so no sender can ever overwrite a buffer its
  neighbor still reads — correct by construction, at n-1 buffers of
  memory.  Production keeps TWO buffers and adds ACK credits: the
  receiver raises an ACK semaphore per consumed buffer and the sender
  blocks until it holds a credit (the reference's signal flags gate
  buffer reuse the same way, ``reduce_scatter.py:688-882``).  A naive
  single/double buffer WITHOUT credits races exactly when one rank runs
  ahead — the bug class tutorial 01's rule 3 warns about.
* **Wait for your own send.**  Overwriting the accumulator while the
  outgoing DMA still reads it is the subtle local race; the kernel
  marks where ``wait_send`` guards it.
* **Chunking.**  Production splits rows into tiles so the first add
  starts before the whole shard arrives, and overlaps each tile's wire
  with the previous tile's add.

Both kernels are checked against the stacked-partials golden, and step 3
verifies the AG<->RS adjoint identity that the fused collective GEMMs'
backward passes ride.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import functools

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.comm import all_gather, reduce_scatter
from triton_distributed_tpu.core import compilation
from triton_distributed_tpu.lang import primitives as dl
from triton_distributed_tpu.lang.primitives import Team

N = 8
M, R = 8, 128   # rows per rank-chunk, row width (keep last dim at 128)


def ring_rs_kernel(team, x_ref, out_ref, acc, recv_bufs, send_sem,
                   recv_sems):
    """Minimal add-as-you-forward ring.  ``x_ref``: my (N*M, R) stacked
    partials in ANY space; ``acc``: the partial I am about to send;
    ``recv_bufs``: ONE receive slot PER STEP.  Distinct slots make the
    kernel race-free by construction — a sender can never overwrite a
    buffer its neighbor is still reading, however far ahead it runs.
    Production cannot afford n-1 live buffers, so it keeps TWO and adds
    the ACK-credit handshake that bounds sender/receiver skew instead;
    that credit protocol is exactly what this tutorial kernel trades
    memory to avoid.  ``out_ref``: my (M, R) result chunk."""
    me = team.rank()
    _, right = team.neighbor_ranks()
    right_id = team.device_id(right)

    def run(buf, sem):
        def my_rows(c):
            # my addend for chunk c: rows [c*M, (c+1)*M) of my stack
            dl.local_copy(x_ref.at[pl.ds(c * M, M)], buf, sem).wait()
            return buf[...]

        dl.collective_prologue(team, neighbors_only=True)
        # originate the longest-journey partial: chunk (me - 1) mod n
        c0 = jax.lax.rem(me + jnp.int32(N - 1), jnp.int32(N))
        acc[...] = my_rows(c0)
        for s in range(1, N):
            # ship my accumulator into the right neighbor's step-s slot;
            # my left neighbor fills MY step-s slot symmetrically
            dl.remote_copy(acc, recv_bufs.at[s - 1], send_sem,
                           recv_sems.at[s - 1], right_id)
            dl.wait_recv(recv_bufs.at[s - 1], recv_sems.at[s - 1])
            # my outgoing DMA must finish READING acc before the add
            # below overwrites it (send/overwrite race — the subtle one)
            dl.wait_send(acc, send_sem)
            c = jax.lax.rem(me + jnp.int32(N - s - 1), jnp.int32(N))
            acc[...] = recv_bufs[s - 1] + my_rows(c)
        # after n-1 hops + adds the accumulator IS chunk ``me`` complete
        dl.local_copy(acc, out_ref, sem).wait()

    pl.run_scoped(run, pltpu.VMEM((M, R), jnp.float32),
                  pltpu.SemaphoreType.DMA)


def build_rs(team):
    call = pl.pallas_call(
        functools.partial(ring_rs_kernel, team),
        out_shape=jax.ShapeDtypeStruct((M, R), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((M, R), jnp.float32),
                        pltpu.VMEM((N - 1, M, R), jnp.float32),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA((N - 1,))],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("tutorial"),
        ),
        interpret=compilation.interpret_mode(),
    )
    mesh = mesh_lib.tp_mesh(N)
    return compilation.jit_shard_map(
        call, mesh, in_specs=P("tp", None), out_specs=P("tp", None)
    )


def main():
    mesh = mesh_lib.tp_mesh(N)
    team = Team.of(mesh, "tp")
    x = jax.random.normal(jax.random.key(0), (N * N * M, R),
                          jnp.float32) * 0.1
    xs = mesh_lib.shard(mesh, x, "tp", None)
    want = np.asarray(x).reshape(N, N * M, R).sum(0)   # (N*M, R)

    # 1. inline serial ring: the stacked outputs equal the golden sum
    fn = build_rs(team)
    out = np.asarray(jax.device_get(fn(xs)))           # (N*M, R) stacked
    np.testing.assert_allclose(out, want, atol=1e-4, rtol=1e-4)
    print("inline add-as-you-forward ring == stacked sum         OK")

    # 2. the production double-buffered ACK-credit ring: same contract
    out2 = reduce_scatter(xs, mesh)
    np.testing.assert_allclose(np.asarray(jax.device_get(out2)), want,
                               atol=1e-4, rtol=1e-4)
    print(f"comm.reduce_scatter == stacked sum {tuple(out2.shape)}      OK")

    # 3. RS and AG are adjoints: <AG(y), x> == <y, RS(x)> for every x, y.
    # This identity is why the fused collective GEMMs can swap wire
    # patterns between forward and backward (ops/gemm_rs.py's VJP).
    y = jax.random.normal(jax.random.key(1), (N * M, R), jnp.float32)
    ys = mesh_lib.shard(mesh, y, "tp", None)
    agy = np.asarray(jax.device_get(all_gather(ys, mesh)),
                     dtype=np.float64)           # every rank: the full y
    rsx = np.asarray(jax.device_get(reduce_scatter(xs, mesh)),
                     dtype=np.float64)           # the summed chunks
    x_np = np.asarray(x, dtype=np.float64).reshape(N, N * M, R)
    lhs = float(sum((agy * x_np[r]).sum() for r in range(N)))
    rhs = float((np.asarray(y, dtype=np.float64) * rsx).sum())
    np.testing.assert_allclose(lhs, rhs, rtol=1e-6)
    print("<AG(y), x> == <y, RS(x)> (adjoint pair)               OK")
    print("\nNext: 06 composes RS+AG into the fused two-shot AllReduce; "
          "08 fuses RS INTO the matmul that produces its input.")


if __name__ == "__main__":
    main()
