"""Tutorial 05 — ring ReduceScatter (reference
05/06-reduce-scatter.rst): ACK-credit double-buffered ring; golden vs the
stacked-partials sum.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.comm import reduce_scatter


def main():
    n, m, r = 8, 64, 256
    mesh = mesh_lib.tp_mesh(n)
    x = jax.random.normal(jax.random.key(0), (n * m, r), jnp.float32) * 0.1
    xs = mesh_lib.shard(mesh, x, "tp", None)
    out = reduce_scatter(xs, mesh)
    want = np.asarray(x).reshape(n, m, r).sum(0)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                               atol=1e-4, rtol=1e-4)
    print("ring RS OK:", out.shape)


if __name__ == "__main__":
    main()
