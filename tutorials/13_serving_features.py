"""Tutorial 13 — production serving: decode modes, paged KV, fp8 EP wire.

Three features the reference ships for production inference, and how they
look here:

1. **Decode reduction modes** (reference ``set_fwd('torch'|'triton_dist'|
   'triton_dist_AR')``, ``models/qwen.py:85,143``).  At decode, every
   layer ends in two row-parallel reductions (attention o-proj, MLP
   down-proj).  Their implementation is a latency/bandwidth trade that
   depends on batch size:

   - ``"psum"``   — local GEMM + ``lax.psum``: XLA's fused latency path,
     right at B=1 where the payload is sub-tile;
   - ``"ar"``     — local GEMM + the Pallas fast-AllReduce family
     (one-shot/two-shot by size): the reference's headline decode config,
     1.27-1.37x at B=128-4096 on its hardware;
   - ``"gemm_ar"``— the fully fused GEMM+AllReduce ring (compute hides
     the wire), when B divides the tp degree.

   All three produce the same logits (tested to ~1e-6); switching is one
   call and a re-jit.

2. **Paged KV cache** (reference ``block_table`` through
   ``gqa_fwd_batch_decode``, ``flash_decode.py:587-720``).  The
   contiguous cache gives every sequence ``max_length`` rows and ONE
   shared length — fine for lockstep batches, wasteful and wrong for real
   serving where sequences differ.  The paged cache keeps a pool of
   fixed-size pages, a per-sequence block table, and RAGGED per-sequence
   lengths; the decode kernel gathers physical pages through
   scalar-prefetched index maps, so Mosaic pipelines page DMAs exactly
   like contiguous splits.

3. **fp8 A2A wire** (reference low-latency A2A production config: e4m3
   payload + scale sidecar, its README 137 us case).  MoE expert
   dispatch/combine traffic is the EP bottleneck; quantizing the wire
   halves the bytes while experts still compute in the model dtype.
   Gradients survive: the integer wire carries a straight-through
   estimator (see ``layers/moe.py``).
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import dataclasses

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Engine, ModelConfig

N = 8
CFG = ModelConfig(
    num_layers=1, hidden=128, intermediate=256, num_heads=8, num_kv_heads=8,
    head_dim=32, vocab=256, max_length=64, dtype=jnp.float32,
)


def main():
    mesh = mesh_lib.tp_mesh(N)
    ids = jax.random.randint(jax.random.key(1), (8, 16), 0, CFG.vocab)

    # -- 1. decode modes agree token for token ----------------------------
    toks = {}
    for mode in ("psum", "ar", "gemm_ar"):
        eng = Engine.build(CFG, mesh, key=jax.random.key(0), batch=8,
                           decode_mode=mode)
        toks[mode] = np.asarray(eng.generate(ids, 4))
    assert np.array_equal(toks["psum"], toks["ar"])
    assert np.array_equal(toks["psum"], toks["gemm_ar"])
    print("1. decode modes psum == ar == gemm_ar (greedy tokens)  OK")
    # switching an existing engine re-jits only the decode step:
    eng.set_decode_mode("psum")

    # -- 2. paged cache: same tokens, ragged-capable layout ---------------
    eng_paged = Engine.build(CFG, mesh, key=jax.random.key(0), batch=8,
                             cache_layout="paged", page_size=16)
    toks_paged = np.asarray(eng_paged.generate(ids, 4))
    assert np.array_equal(toks["psum"], toks_paged)
    cache = eng_paged.cache
    print(f"2. paged engine == contiguous engine               OK "
          f"(pool {cache.k.shape[1]} pages x {cache.page_size} slots, "
          f"ragged seq_lens={np.asarray(cache.seq_lens)[:3]}...)")

    # -- 3. MoE EP with the fp8 wire --------------------------------------
    moe_cfg = dataclasses.replace(
        CFG, num_experts=8, top_k=2, moe_intermediate=32,
        moe_strategy="ep",
    )
    logits = {}
    for fp8 in (False, True):
        cfg = dataclasses.replace(moe_cfg, moe_fp8_wire=fp8)
        eng = Engine.build(cfg, mesh, key=jax.random.key(2), batch=8)
        logits[fp8] = np.asarray(eng.prefill(ids))
    err = np.abs(logits[True] - logits[False]).max()
    scale = np.abs(logits[False]).max() + 1e-9
    assert err <= 0.1 * scale, (err, scale)
    from triton_distributed_tpu.layers.moe import _FP8_SIDECAR

    h = moe_cfg.hidden
    full = h * jnp.dtype(moe_cfg.dtype).itemsize
    print(f"3. fp8 EP wire within quantization tolerance       OK "
          f"(rel err {err / scale:.1%}; wire {h + _FP8_SIDECAR} vs "
          f"{full} bytes/token/hop here; at bf16 hidden=7168 the ratio "
          f"is {2 * 7168 / (7168 + _FP8_SIDECAR):.2f}x fewer)")


if __name__ == "__main__":
    main()
