"""Tutorial 09 — long-context attention: SP ring prefill, inter-slice
hierarchy, and distributed flash-decode (contiguous + paged).

Prefill: KV chunks rotate the ring (ppermute) while each rank folds the
resident chunk into a carried online-softmax state — peak memory one extra
chunk, wire overlapped with MXU.  Across SLICES, the hierarchical variant
runs a full ICI ring per slice per outer step and hops the slice-resident
chunk set over DCN only n_out - 1 times (reference inter-node SP
attention, ``sp_ag_attention_inter_node.py``).

Decode: each rank runs split-KV over its cache slice; the tiny
(num, max, den) softmax states merge associatively across splits AND
ranks — the paged variant reads its slice through a block table with
ragged per-sequence lengths (reference ``sp_flash_decode_layer.py``).
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.ops import (
    decode_attention,
    flash_attention,
    hierarchical_sp_attention,
    sp_attention,
    sp_flash_decode,
    sp_paged_flash_decode,
)


def main():
    n, b, h, hk, s, d = 8, 1, 8, 4, 1024, 64
    mesh = mesh_lib.make_mesh({"sp": n}, devices=jax.devices()[:n])
    kq, kk, kv, kd = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.float32)
    spec = NamedSharding(mesh, P(None, None, "sp", None))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))

    out = sp_attention(qs, ks, vs, mesh, axis="sp", causal=True,
                       block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(want), atol=2e-5, rtol=2e-5)
    print("1. SP ring prefill OK:", out.shape)

    # inter-slice: 2 slices x 4 devices; same math, DCN traffic bounded
    hmesh = jax.sharding.Mesh(
        np.array(jax.devices()[:n]).reshape(2, n // 2), ("dcn", "ici")
    )
    hspec = NamedSharding(hmesh, P(None, None, ("dcn", "ici"), None))
    qh, kh, vh = (jax.device_put(t, hspec) for t in (q, k, v))
    outh = hierarchical_sp_attention(qh, kh, vh, hmesh, "ici", "dcn",
                                     causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(jax.device_get(outh)),
                               np.asarray(want), atol=2e-5, rtol=2e-5)
    print("2. hierarchical (2 slices x 4) prefill OK")

    qd = jax.random.normal(kd, (b, h, d), jnp.float32)
    outd = sp_flash_decode(qd, ks, vs, 900, mesh, axis="sp", n_split=2)
    wantd = decode_attention(qd, k, v, 900)
    np.testing.assert_allclose(np.asarray(jax.device_get(outd)),
                               np.asarray(wantd), atol=2e-5, rtol=2e-5)
    print("3. SP flash-decode OK:", outd.shape)

    # paged: each rank's slice lives in 4 pages of 32 rows, addressed
    # through a per-rank block table (identity map here; any bijection
    # works — see tests/test_paged_cache.py for randomized maps)
    ps, mp = 32, (s // n) // 32
    pool_k = np.asarray(k).reshape(b, hk, n, mp, ps, d)[0].transpose(
        1, 2, 0, 3, 4
    ).reshape(n * mp, hk, ps, d)
    pool_v = np.asarray(v).reshape(b, hk, n, mp, ps, d)[0].transpose(
        1, 2, 0, 3, 4
    ).reshape(n * mp, hk, ps, d)
    tables = np.broadcast_to(
        np.arange(mp, dtype=np.int32)[None, None, :], (n, b, mp)
    ).copy()
    outp = sp_paged_flash_decode(
        qd, jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(tables),
        jnp.asarray([900], np.int32), mesh, axis="sp",
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(outp)),
                               np.asarray(wantd), atol=2e-5, rtol=2e-5)
    print("4. paged SP flash-decode (block table, ragged lens) OK")

    # 5. PACKED VARIABLE-LENGTH batches (the reference's cu_seqlens,
    # re-expressed as segment ids): three sequences packed into one row
    # attend only within their own segment.  The KV segment ids rotate
    # with the chunks through the flat ring AND through both levels of
    # the hierarchical path — a long-context serving batch stays packed
    # across slices.
    segs = jnp.asarray(
        np.repeat([0, 1, 2], [s // 2, s // 4, s // 4])[None], jnp.int32
    )
    segd = jax.device_put(segs, NamedSharding(mesh, P(None, "sp")))
    want_vl = flash_attention(q, k, v, causal=True, segment_ids=segs,
                              block_q=128, block_k=128)
    out_vl = sp_attention(qs, ks, vs, mesh, axis="sp", causal=True,
                          segment_ids=segd, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(jax.device_get(out_vl)),
                               np.asarray(want_vl), atol=2e-5, rtol=2e-5)
    segh = jax.device_put(segs, NamedSharding(hmesh, P(None, ("dcn", "ici"))))
    outh_vl = hierarchical_sp_attention(
        qh, kh, vh, hmesh, "ici", "dcn", causal=True, segment_ids=segh,
        block_q=128, block_k=128,
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(outh_vl)),
                               np.asarray(want_vl), atol=2e-5, rtol=2e-5)
    print("5. packed varlen batch through flat ring AND hierarchy OK")


if __name__ == "__main__":
    main()
