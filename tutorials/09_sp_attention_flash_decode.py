"""Tutorial 09 — long-context attention: SP ring prefill + distributed
flash-decode.

Prefill: KV chunks rotate the ring (ppermute) while each rank folds the
resident chunk into a carried online-softmax state — peak memory one extra
chunk, wire overlapped with MXU.  Decode: each rank runs split-KV over its
cache slice; the tiny (num, max, den) states merge associatively.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.ops import (
    decode_attention,
    flash_attention,
    sp_attention,
    sp_flash_decode,
)


def main():
    n, b, h, hk, s, d = 8, 1, 8, 4, 1024, 64
    mesh = mesh_lib.make_mesh({"sp": n}, devices=jax.devices()[:n])
    kq, kk, kv, kd = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.float32)
    spec = NamedSharding(mesh, P(None, None, "sp", None))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))

    out = sp_attention(qs, ks, vs, mesh, axis="sp", causal=True,
                       block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(want), atol=2e-5, rtol=2e-5)
    print("SP ring prefill OK:", out.shape)

    qd = jax.random.normal(kd, (b, h, d), jnp.float32)
    outd = sp_flash_decode(qd, ks, vs, 900, mesh, axis="sp", n_split=2)
    wantd = decode_attention(qd, k, v, 900)
    np.testing.assert_allclose(np.asarray(jax.device_get(outd)),
                               np.asarray(wantd), atol=2e-5, rtol=2e-5)
    print("SP flash-decode OK:", outd.shape)


if __name__ == "__main__":
    main()
