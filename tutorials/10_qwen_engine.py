"""Tutorial 10 — the end-to-end story: a Qwen3-style TP model served by the
engine (prefill fills the head-sharded KV cache through the fused layer
path; decode replays the jitted, cache-donating step), plus autotuning and
profiling around it.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Engine, ModelConfig
from triton_distributed_tpu.tools import gemm_sol_ms, group_profile


def main():
    cfg = ModelConfig(num_layers=2, hidden=64, intermediate=128,
                      num_heads=4, num_kv_heads=2, head_dim=32, vocab=128,
                      max_length=64, dtype=jnp.float32)
    mesh = mesh_lib.tp_mesh(2)
    eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=1,
                       temperature=0.7, top_p=0.9)
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab)
    with group_profile("qwen-serve", "/tmp/tdt_tutorial_trace"):
        out = eng.generate(ids, gen_len=8, key=jax.random.key(2))
    print("generated tokens:", np.asarray(out))
    sol = gemm_sol_ms(4096, 4096, 4096, device_kind="TPU v5e")
    print(f"(for scale: a 4096^3 bf16 GEMM is {sol:.2f} ms at v5e SOL)")


if __name__ == "__main__":
    main()
