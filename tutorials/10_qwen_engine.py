"""Tutorial 10 — serving a Qwen3-style TP model with the Engine
(reference ``engine.py:37-136``, ``qwen.py:54-143``).

Everything the earlier tutorials built — fused AG-GEMM/GEMM-RS layers
(07/08), flash attention, the AllReduce family (06) — assembles here
into the serving loop.  The engine's three moving parts, and what each
translates from the reference:

* **Prefill** runs the prompt through the FUSED layer path (AG-GEMM in,
  GEMM-RS out) and fills the head-sharded KV cache.  Head sharding
  means each TP rank stores only its kv-heads' cache — the cache
  scales down with TP exactly like the weights.
* **Decode** is one token per call through latency-shaped kernels
  (split-KV decode attention against the cache).  The reference
  captures its decode step in a CUDA graph so replay costs no host
  work; the TPU analogue is ``jax.jit`` with the cache DONATED
  (``donate_argnums``): the executable updates the cache buffers in
  place and replays without re-tracing.  First call = capture
  (compile), every later call = replay.
* **decode_mode** switches the decode step's row-parallel reductions:
  ``psum`` (XLA's fused collective), ``ar`` (this framework's one-shot
  push AllReduce — the latency winner at decode sizes), or ``gemm_ar``
  (the fully fused GEMM+AllReduce ring).  This is the reference's
  ``set_fwd('torch'|'triton_dist')`` switch; all three produce the
  same logits (asserted below), and ``bench.py decode_modes`` records
  their per-step wire volumes.

Sampling (greedy / temperature / top-p nucleus) is the reference's
``sample_token``, in jnp.  Around the loop: the autotuner's winner
cache is consulted by every ``config=None`` op inside the jitted step
(tutorial 07), and ``tools.group_profile`` captures a trace you can
open in Perfetto/XProf.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import numpy as np

import jax.numpy as jnp

from triton_distributed_tpu.models import Engine, ModelConfig
from triton_distributed_tpu.models.engine import sample_token
from triton_distributed_tpu.tools import gemm_sol_ms, group_profile


def main():
    cfg = ModelConfig(num_layers=2, hidden=64, intermediate=128,
                      num_heads=4, num_kv_heads=2, head_dim=32, vocab=128,
                      max_length=64, dtype=jnp.float32)
    mesh = mesh_lib.tp_mesh(2)

    # 1. build = init sharded params + cache + jit (the "CUDA-graph
    # capture").  batch and max_length fix the decode step's shapes: one
    # executable serves the whole session.
    eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=1,
                       temperature=0.7, top_p=0.9)
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab)

    # 2. the serve loop under a profiler capture
    with group_profile("qwen-serve", "/tmp/tdt_tutorial_trace"):
        out = eng.generate(ids, gen_len=8, key=jax.random.key(2))
    print("generated tokens:", np.asarray(out))

    # 3. decode_mode parity: the reference's set_fwd switch.  Greedy
    # sampling so the argmax chain must match token for token.
    tokens = {}
    for mode in ("psum", "ar", "gemm_ar"):
        e = Engine.build(cfg, mesh, key=jax.random.key(0), batch=1,
                         decode_mode=mode)
        tokens[mode] = np.asarray(e.generate(ids, gen_len=8))
    np.testing.assert_array_equal(tokens["psum"], tokens["ar"])
    np.testing.assert_array_equal(tokens["psum"], tokens["gemm_ar"])
    print("decode modes psum == ar == gemm_ar (greedy tokens)    OK")

    # 4. the paged cache layout (the reference's production decode
    # layout): a page pool + block table + ragged per-sequence lengths
    # behind the same Engine API
    ep = Engine.build(cfg, mesh, key=jax.random.key(0), batch=1,
                      cache_layout="paged", page_size=16)
    paged = np.asarray(ep.generate(ids, gen_len=8))
    np.testing.assert_array_equal(paged, tokens["psum"])
    print("paged cache == contiguous cache (greedy tokens)       OK")

    # 5. sampling: greedy vs nucleus on a fixed logit row
    logits = jnp.asarray([[0.0, 2.0, 1.0, -1.0]])
    greedy = sample_token(logits, jax.random.key(0))
    nucl = sample_token(logits, jax.random.key(0), temperature=0.8,
                        top_p=0.5)
    assert greedy.shape == nucl.shape == (1,)
    print(f"sampling: greedy -> {int(greedy[0])}, "
          f"top_p=0.5 -> {int(nucl[0])} (masked to the nucleus)")

    sol = gemm_sol_ms(4096, 4096, 4096, device_kind="TPU v5e")
    print(f"\n(for scale: a 4096^3 bf16 GEMM is {sol:.2f} ms at v5e SOL; "
          f"tools/perf_model.py prices every kernel here the same way)")
    print("Next: 11 swaps the MLP for routed MoE experts; 13 tours the "
          "serving features (ragged batches, paged decode, streaming).")


if __name__ == "__main__":
    main()
