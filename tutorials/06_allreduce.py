"""Tutorial 06 — AllReduce family + fused GEMM+AR.

One-shot (full-mesh push + local f32 reduce, latency-optimal) vs fused
two-shot (RS ring + AG ring in ONE kernel, bandwidth-optimal), and the
fused row-parallel GEMM+AllReduce.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import AllReduceMethod, all_reduce
from triton_distributed_tpu.ops import gemm_ar


def main():
    n, m, r = 8, 64, 256
    mesh = mesh_lib.tp_mesh(n)
    x = jax.random.normal(jax.random.key(0), (n * m, r), jnp.float32) * 0.1
    xs = mesh_lib.shard(mesh, x, "tp", None)
    want = np.asarray(x).reshape(n, m, r).sum(0)
    for method in (AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT):
        out = all_reduce(xs, mesh, method=method)
        np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                                   atol=1e-4, rtol=1e-4)
        print(f"{method.value:9s} OK")

    mm, k, nn = 64, 256, 128
    a = jax.random.normal(jax.random.key(1), (mm, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(2), (k, nn), jnp.float32) * 0.1
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))
    b_s = jax.device_put(b, NamedSharding(mesh, P("tp", None)))
    out = gemm_ar(a_s, b_s, mesh)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(a @ b), atol=1e-3, rtol=1e-3)
    print("fused gemm_ar OK")


if __name__ == "__main__":
    main()
