"""Tutorial 06 — the AllReduce family + fused GEMM+AR (reference
``allreduce.py:28,224-693``, ``e2e_dense.md`` "GEMM + AllReduce").

AllReduce = everyone ends with the SUM of everyone's partials.  Two
algorithms span the latency/bandwidth trade, exactly as in tutorials
02/05 — because an AllReduce IS a ReduceScatter followed by an
AllGather:

* **ONE_SHOT** — every rank pushes its whole partial to every peer and
  reduces locally in f32.  Per rank: ``(n-1) * nbytes`` sent, ONE hop.
  The latency choice: a decode step's (B, H) activation is ~100 KB and
  hop latency dominates; this is the reference's choice at decode sizes
  and what ``models/qwen.py``'s ``decode_mode="ar"`` rides.
* **TWO_SHOT** — an RS ring then an AG ring, FUSED into one kernel (no
  intermediate HBM round trip between the phases; the AG forwards
  chunks as soon as their reduction completes).  Per rank:
  ``2 (n-1)/n * nbytes`` — n/2x less wire than one-shot — across
  2(n-1) latency-chained hops.  The bandwidth choice for prefill-sized
  tensors.

The size crossover lives in ``comm.allreduce.choose_method`` and is the
same reasoning as the reference's nbytes switch (``allreduce.py:1042``).

Below you will:

1. check both algorithms against the stacked-partials golden;
2. DERIVE two-shot from tutorials 02+05 — compose the production
   ``reduce_scatter`` and ``all_gather`` and confirm the fused kernel
   computes exactly that composition;
3. print the per-rank wire table that drives the auto-selection;
4. run the fused GEMM+AllReduce (``ops/gemm_ar.py`` — the op behind the
   reference's headline decode win) and differentiate through it.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import (
    AllReduceMethod, all_gather, all_reduce, reduce_scatter,
)
from triton_distributed_tpu.comm.allreduce import choose_method
from triton_distributed_tpu.ops import gemm_ar

N = 8


def main():
    n, m, r = N, 64, 256
    mesh = mesh_lib.tp_mesh(n)
    x = jax.random.normal(jax.random.key(0), (n * m, r), jnp.float32) * 0.1
    xs = mesh_lib.shard(mesh, x, "tp", None)
    want = np.asarray(x).reshape(n, m, r).sum(0)

    # 1. both algorithms against the stacked-partials golden.  Note the
    # one-shot reduces in f32 regardless of input dtype — n-way bf16
    # adds in arrival order would drift with n.
    for method in (AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT):
        out = all_reduce(xs, mesh, method=method)   # (m, r), replicated
        np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                                   atol=1e-4, rtol=1e-4)
        print(f"all_reduce {method.value:9s} == stacked sum          OK")

    # 2. two-shot IS RS-then-AG: the fused kernel must equal the
    # composition of the two production rings from tutorials 05 and 02
    composed = all_gather(reduce_scatter(xs, mesh), mesh)
    fused = all_reduce(xs, mesh, method=AllReduceMethod.TWO_SHOT)
    np.testing.assert_allclose(np.asarray(jax.device_get(fused)),
                               np.asarray(jax.device_get(composed)),
                               atol=1e-5, rtol=1e-5)
    print("fused two-shot == all_gather(reduce_scatter(x))       OK")

    # 3. the wire table behind the auto-selection (per rank, per AR)
    print("\n  per-rank wire bytes      one_shot        two_shot   auto")
    for nbytes in (64 * 1024, 512 * 1024, 16 * 2**20):
        one = (n - 1) * nbytes
        two = int(2 * (n - 1) / n * nbytes)
        pick = choose_method(nbytes, n).value
        print(f"  {nbytes:>12,} B   {one:>12,} B {two:>12,} B   {pick}")
    print()

    # 4. the fused row-parallel GEMM+AllReduce: each rank multiplies its
    # K-shard and the ring reduces+replicates the partials while later
    # chunks are still on the MXU.  This op (switched in by
    # Engine.set_decode_mode("gemm_ar")) is the TPU form of the
    # reference's "GEMM + AllReduce" decode headline.
    mm, k, nn = 64, 256, 128
    a = jax.random.normal(jax.random.key(1), (mm, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(2), (k, nn), jnp.float32) * 0.1
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))
    b_s = jax.device_put(b, NamedSharding(mesh, P("tp", None)))
    out = gemm_ar(a_s, b_s, mesh)                   # (mm, nn), replicated
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(a @ b), atol=1e-3, rtol=1e-3)
    print("fused gemm_ar == a @ b (replicated on every rank)     OK")

    # gradients through the fused op match the dense matmul's
    def loss_fused(a_, b_):
        return (gemm_ar(a_, b_, mesh).astype(jnp.float32) ** 2).sum()

    ga_f, gb_f = jax.grad(loss_fused, argnums=(0, 1))(a_s, b_s)
    ga_d, gb_d = jax.grad(
        lambda a_, b_: ((a_ @ b_) ** 2).sum(), argnums=(0, 1)
    )(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(ga_f)),
                               np.asarray(ga_d), atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(jax.device_get(gb_f)),
                               np.asarray(gb_d), atol=2e-2, rtol=2e-2)
    print("grad through fused gemm_ar == dense matmul grad       OK")
    print("\nNext: 10 switches a real model's decode step between psum / "
          "ar / gemm_ar with Engine.set_decode_mode.")


if __name__ == "__main__":
    main()
