"""Tutorial 11 — serving a Qwen3-MoE model under both expert strategies
(reference ``allgather_group_gemm.py``, ``moe_reduce_rs.py``,
``ep_a2a_layer.py``; the reference's model zoo has no MoE model — this
engine-level integration is beyond-parity).

A routed MoE layer replaces the dense MLP: a router scores every token
against E experts, the top-k win, and each token's output is the
routing-weighted sum of its k experts' SwiGLU outputs.  The work is
ragged by construction — expert loads depend on the data — and HOW the
ragged work is laid out across ranks is a choice between two dataflows,
both built from earlier tutorials:

* ``moe_strategy="tp"`` — EXPERTS STAY, TOKENS GATHER.  Every rank
  holds all E experts, feature-sharded.  Tokens AllGather over the
  ranks (tutorial 02), are sorted into expert order, hit the grouped
  matmul (the pad-eliding tile-scheduled Pallas kernel at
  ``ops/group_gemm.py``), and ReduceScatter home (tutorial 05).  Wire
  scales with the TOKEN count; expert weights never move.
* ``moe_strategy="ep"`` — TOKENS TRAVEL TO THEIR EXPERTS.  Experts are
  partitioned across ranks; each token's hidden vector rides the A2A
  to its experts' owners and the results ride back (tutorial 04's
  dispatch/combine).  Wire scales with k * tokens * hidden, but the
  grouped matmuls are purely local — the production layout when
  experts outnumber what one rank can hold.  ``moe_fp8_wire=True``
  halves that wire by shipping e4m3 payloads + f32 scale sidecars in
  one u8 message on BOTH hops (the reference's production A2A config).

The strategies are LAYOUTS of one mathematical layer, so the engine
must produce identical tokens under either — asserted below, including
the fp8-wire variant (quantized wire, greedy argmax unchanged at these
scales) and the gradient path through routing.
"""

import dataclasses

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Engine, ModelConfig, Qwen3


def main():
    cfg = ModelConfig(num_layers=2, hidden=64, intermediate=128,
                      num_heads=8, num_kv_heads=4, head_dim=32, vocab=128,
                      max_length=64, dtype=jnp.float32,
                      num_experts=8, top_k=2, moe_intermediate=32)
    mesh = mesh_lib.tp_mesh(4)
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab)

    # 1. the same logical model (same init seed) under both layouts
    tokens = {}
    for strategy in ("tp", "ep"):
        c = dataclasses.replace(cfg, moe_strategy=strategy)
        model = Qwen3(c, mesh)
        params = model.init(jax.random.key(0))
        eng = Engine(model, params, batch=1)
        out, stats = eng.serve(ids, gen_len=8)
        tokens[strategy] = np.asarray(jax.device_get(out))
        print(f"{strategy}: tokens={tokens[strategy][0].tolist()} "
              f"decode={stats['decode_ms_per_token']:.1f} ms/tok")
    np.testing.assert_array_equal(tokens["tp"], tokens["ep"])
    print("tp and ep strategies agree token-for-token            OK")

    # 2. the fp8 wire (EP only): e4m3 + scale sidecar on both A2A hops.
    # Quantization perturbs activations by <1% — far inside the greedy
    # argmax margin at these scales, so tokens still match exactly.
    c8 = dataclasses.replace(cfg, moe_strategy="ep", moe_fp8_wire=True)
    model8 = Qwen3(c8, mesh)
    eng8 = Engine(model8, model8.init(jax.random.key(0)), batch=1)
    t8 = np.asarray(jax.device_get(eng8.generate(ids, gen_len=8)))
    np.testing.assert_array_equal(t8, tokens["ep"])
    h = cfg.hidden
    print(f"fp8 wire on: tokens unchanged                         OK\n"
          f"  (the 128-B scale sidecar dominates at toy hidden={h}: "
          f"{2 * h} -> {h + 128} B/token/hop; at production hidden=7168 "
          f"it amortizes: {2 * 7168} -> {7168 + 128} B = "
          f"{2 * 7168 / (7168 + 128):.2f}x fewer — bench.py moe_ep "
          f"measures the codec itself)")

    # 3. the wire-volume argument that picks a strategy, per MoE layer
    # forward at T tokens/rank, n=4 ranks, top-k=2 (bf16 wire):
    t_tok, n, k = 512, 4, cfg.top_k
    tp_wire = 2 * (n - 1) * t_tok * h * 2          # AG tokens + RS partials
    ep_wire = 2 * k * (n - 1) / n * t_tok * h * 2  # dispatch + combine
    print(f"\n  per-rank wire per layer at T={t_tok}: "
          f"tp(AG+RS) {tp_wire:,} B vs ep(A2A x2) {int(ep_wire):,} B"
          f"\n  (ep wins when top_k < n; fp8 halves the ep number again)")

    # 4. training flows through routing, ragged grouped matmuls, and the
    # A2A (dispatch/combine are each other's adjoints — tutorial 04):
    # one grad through the EP MoE layer is nonzero end to end
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.layers.moe import MoEMLP

    layer = MoEMLP(mesh, num_experts=8, top_k=2, swiglu=True)
    rng = np.random.default_rng(0)
    hid, ffn, t4 = 32, 16, 8
    xs = jax.device_put(
        jnp.asarray(rng.standard_normal((4 * t4, hid)), jnp.float32) * 0.3,
        NamedSharding(mesh, P("tp", None)))
    p_ep = layer.shard_params_ep(
        jnp.asarray(rng.standard_normal((hid, 8)), jnp.float32),
        layer.fuse_expert_gate_up(
            jnp.asarray(rng.standard_normal((8, hid, ffn)), jnp.float32) * .3,
            jnp.asarray(rng.standard_normal((8, hid, ffn)), jnp.float32) * .3,
            ep=True),
        jnp.asarray(rng.standard_normal((8, ffn, hid)), jnp.float32) * 0.3,
    )
    grads = jax.jit(jax.grad(
        lambda p, x: jnp.mean(layer.forward_ep(p, x) ** 2)
    ))(p_ep, xs)
    gnorm = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads))
    assert gnorm > 0, "gradients must flow through routing + A2A"
    print(f"\ngrad through EP MoE layer: L1 norm {gnorm:.2f} > 0    OK")
    print("\nNext: 12 runs full training steps (TP, MoE-TP, MoE-EP, "
          "pipeline); 04 has the A2A internals these layers ride.")


if __name__ == "__main__":
    main()
