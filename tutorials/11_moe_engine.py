"""Tutorial 11 — serving a Qwen3-MoE model under both expert strategies.

The same model (same init seed, so identical logical weights) serves
under:

- ``moe_strategy="tp"``: every rank holds all experts F-sharded; prefill
  routes through AG + group-GEMM (the tile-scheduled Pallas grouped
  matmul on real TPU) + RS;
- ``moe_strategy="ep"``: experts partitioned across ranks; prefill
  dispatches tokens to their experts' owners over the A2A and combines
  the results back.

Both must produce identical tokens — the strategy is a layout choice,
not a model change.
"""

import dataclasses

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Engine, ModelConfig, Qwen3


def main():
    cfg = ModelConfig(num_layers=2, hidden=64, intermediate=128,
                      num_heads=8, num_kv_heads=4, head_dim=32, vocab=128,
                      max_length=64, dtype=jnp.float32,
                      num_experts=8, top_k=2, moe_intermediate=32)
    mesh = mesh_lib.tp_mesh(4)
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab)

    tokens = {}
    for strategy in ("tp", "ep"):
        c = dataclasses.replace(cfg, moe_strategy=strategy)
        model = Qwen3(c, mesh)
        # same seed -> same logical weights; only the layout differs.
        # (For the "ep" run the init shards experts instead of features.)
        params = model.init(jax.random.key(0))
        eng = Engine(model, params, batch=1)
        out, stats = eng.serve(ids, gen_len=8)
        tokens[strategy] = np.asarray(jax.device_get(out))
        print(f"{strategy}: tokens={tokens[strategy][0].tolist()} "
              f"decode={stats['decode_ms_per_token']:.1f} ms/tok")

    np.testing.assert_array_equal(tokens["tp"], tokens["ep"])
    print("tp and ep strategies agree token-for-token")


if __name__ == "__main__":
    main()
