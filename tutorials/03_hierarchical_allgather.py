"""Tutorial 03 — two-level (ICI + DCN) AllGather (reference
03-inter-node-allgather.rst).

Within a slice the Pallas ring rides ICI remote DMA; across slices there
is no device-initiated DMA, so the outer level rides XLA's DCN
collectives — the standard TPU multi-slice split.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.allgather import hierarchical_all_gather
from triton_distributed_tpu.comm.allreduce import hierarchical_all_reduce
from triton_distributed_tpu.comm.reduce_scatter import (
    hierarchical_reduce_scatter,
)


def main():
    mesh = mesh_lib.make_mesh({"dcn": 2, "ici": 4},
                              devices=jax.devices()[:8])
    x = jax.random.normal(jax.random.key(0), (8 * 16, 256), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), np.asarray(x))
    print("hierarchical (2x4) AG OK")

    # the whole two-level family shares the shape convention: inner level
    # on the ICI Pallas rings, outer level on XLA's DCN collectives
    want = np.asarray(x).reshape(8, 16, 256).sum(0)
    rs = hierarchical_reduce_scatter(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(rs)), want,
                               rtol=1e-5, atol=1e-5)
    print("hierarchical (2x4) RS OK")
    ar = hierarchical_all_reduce(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(ar)), want,
                               rtol=1e-5, atol=1e-5)
    print("hierarchical (2x4) AR OK")


if __name__ == "__main__":
    main()
