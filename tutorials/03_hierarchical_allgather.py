"""Tutorial 03 — two-level (ICI + DCN) AllGather (reference
03-inter-node-allgather.rst).

Within a slice the Pallas ring rides ICI remote DMA; across slices there
is no device-initiated DMA, so the outer level rides XLA's DCN
collectives — the standard TPU multi-slice split.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.allgather import hierarchical_all_gather


def main():
    mesh = mesh_lib.make_mesh({"dcn": 2, "ici": 4},
                              devices=jax.devices()[:8])
    x = jax.random.normal(jax.random.key(0), (8 * 16, 256), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), np.asarray(x))
    print("hierarchical (2x4) AG OK")


if __name__ == "__main__":
    main()
