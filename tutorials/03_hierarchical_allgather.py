"""Tutorial 03 — two-level (ICI x DCN) collectives (reference
03-inter-node-allgather.rst).

Tutorial 02's rings assumed every peer is reachable by remote DMA.
That is true WITHIN a TPU slice (the ICI torus) and false ACROSS slices:
a pod's slices talk over the data-center network (DCN), and TPU remote
DMA is device-initiated over ICI only.  The reference faces the same
split on GPU clusters — NVLink inside a node, IB/Ethernet across — and
its 2D AllGather stages intra-node copy-engine rings against cross-node
transfers (``allgather.py:442-601``).

The TPU mapping (``comm/allgather.py::hierarchical_all_gather``):

* **inner level (ICI)** — this framework's Pallas ring/push kernels,
  exactly tutorial 02's, run independently inside each slice;
* **outer level (DCN)** — ``lax.all_gather`` over the outer mesh axis:
  XLA owns the DCN transport, so the cross-slice hop is its collective;
* **ordering contract** — rows come back in GLOBAL rank order
  (outer-major), indistinguishable from a flat AG over one combined
  axis.  Layers built on flat AG move to a 2-level mesh untouched.

Why stage at all, instead of one flat ring over all n_out*n_in ranks?
DCN bandwidth is an order of magnitude below ICI, and its hop latency
is worse still.  A flat ring takes n_out*n_in - 1 LATENCY-CHAINED hops,
and in the worst placement every one of them crosses the DCN.  Staged,
the inner AG runs entirely on ICI, and the DCN carries ONE outer
collective — each chip ships its slice's gathered block
((n_out - 1) * n_in shard-sizes, vs the flat worst case's
n_out*n_in - 1) in a single XLA-scheduled exchange instead of a serial
hop chain.  The same asymmetry argument shapes
``hierarchical_sp_attention``'s superchunk rotation (tutorial 09).

Below: the mesh-layout convention, golden checks for AG/RS/AR, the
flat-vs-staged equivalence, and the DCN wire accounting that justifies
the staging.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.allgather import (
    all_gather, hierarchical_all_gather,
)
from triton_distributed_tpu.comm.allreduce import hierarchical_all_reduce
from triton_distributed_tpu.comm.reduce_scatter import (
    hierarchical_reduce_scatter,
)

N_OUT, N_IN = 2, 4            # 2 slices x 4 chips (simulated on 8 devices)
M, R = 16, 256                # rows per device, row width


def main():
    n = N_OUT * N_IN
    # the axis ORDER in the mesh dict is the layout contract: outer
    # (DCN) axis first, so P(("dcn", "ici")) shards dim 0 outer-major —
    # device (o, i) holds rows [(o*N_IN + i) * M, ...).  core/mesh.py's
    # DCN prefix convention automates this on real multi-slice topologies.
    mesh = mesh_lib.make_mesh({"dcn": N_OUT, "ici": N_IN},
                              devices=jax.devices()[:n])
    x = jax.random.normal(jax.random.key(0), (n * M, R), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))

    # 1. hierarchical AG == the full input, in global rank order
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(x))
    print("hierarchical (2x4) AllGather == full input            OK")

    # 2. and == the FLAT AG over a combined 8-rank axis (the ordering
    # contract: staging is invisible to the caller)
    flat_mesh = mesh_lib.tp_mesh(n)
    flat = all_gather(mesh_lib.shard(flat_mesh, x, "tp", None), flat_mesh)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                               np.asarray(jax.device_get(flat)))
    print("staged 2-level AG == flat single-axis AG              OK")

    # 3. the whole family shares the convention: RS and AR stage the
    # same way (inner Pallas ring, outer XLA collective)
    want = np.asarray(x).reshape(n, M, R).sum(0)
    rs = hierarchical_reduce_scatter(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(rs)), want,
                               rtol=1e-5, atol=1e-5)
    print("hierarchical ReduceScatter == stacked sum             OK")
    ar = hierarchical_all_reduce(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(ar)), want,
                               rtol=1e-5, atol=1e-5)
    print("hierarchical AllReduce == stacked sum                 OK")

    # 4. the DCN accounting.  For the AG of an (M, R) f32 shard, the
    # implementation (comm/allgather.py::_build_hierarchical) gathers the
    # slice over ICI FIRST, then outer-AllGathers the (N_IN * M, R)
    # slice block over DCN — so each chip's DCN traffic is
    # (N_OUT - 1) * N_IN shard-sizes, in ONE XLA-scheduled exchange,
    # vs the flat ring's worst case of n - 1 shard-sizes across n - 1
    # LATENCY-CHAINED hops.  The byte win is modest; the latency win
    # (one DCN exchange vs a serial hop chain through the slow links)
    # is the point.
    nbytes = M * R * 4
    flat_dcn = (n - 1) * nbytes
    staged_dcn = (N_OUT - 1) * N_IN * nbytes
    print(f"\n  per-chip DCN bytes, worst-case flat ring: {flat_dcn:,} "
          f"across {n - 1} serial hops"
          f"\n  per-chip DCN bytes, staged:               {staged_dcn:,} "
          f"in 1 outer exchange"
          f"\n  (all {N_IN - 1} repeated hops per chunk ride the fast ICI)")
    print("\nNext: 09's hierarchical SP attention applies the same "
          "ICI-inner / DCN-outer staging to ring attention's KV rotation.")


if __name__ == "__main__":
    main()
