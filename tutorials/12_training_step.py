"""Tutorial 12 — training through the fused collective ops.

The custom VJPs make the whole stack differentiable, riding the TP
adjoint duality: AllGather's transpose is ReduceScatter, so
``ag_gemm``'s backward runs ``gemm_rs`` (and vice versa), keeping the
backward pass's communication overlapped exactly like the forward's;
the EP A2A dispatch/combine pair are likewise each other's adjoints.

Here: an optax Adam loop over the fused TP MLP layer and over the
routed MoE layer, on the simulated mesh — the identical code trains on
a real slice.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.layers import TPMLP
from triton_distributed_tpu.layers.moe import MoEMLP


def train(loss_fn, params, steps=8, lr=3e-3):
    opt = optax.adam(lr)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, loss

    first = last = None
    for _ in range(steps):
        params, state, loss = step(params, state)
        last = float(loss)
        first = last if first is None else first
    return first, last


def main():
    mesh = mesh_lib.tp_mesh(4)
    rng = np.random.default_rng(0)
    m, k, i = 32, 64, 64

    # dense TP MLP: fit random targets
    layer = TPMLP(mesh)
    params = layer.init(jax.random.key(0), k, i, dtype=jnp.float32,
                        scale=0.3)
    x = jax.device_put(
        jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3),
        NamedSharding(mesh, P("tp", None)),
    )
    target = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.1)

    first, last = train(
        lambda p: jnp.mean((layer.forward(p, x) - target) ** 2), params
    )
    print(f"TP MLP:  loss {first:.5f} -> {last:.5f}")
    assert last < first

    # routed MoE (SwiGLU experts, TP strategy)
    moe = MoEMLP(mesh, num_experts=8, top_k=2, swiglu=True)
    mparams = moe.init(jax.random.key(1), k, 32, dtype=jnp.float32,
                       scale=0.3)
    first, last = train(
        lambda p: jnp.mean((moe.forward_tp(p, x) - target) ** 2), mparams
    )
    print(f"MoE TP:  loss {first:.5f} -> {last:.5f}")
    assert last < first
    print("both layers train through the fused collectives")


if __name__ == "__main__":
    main()
