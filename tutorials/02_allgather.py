"""Tutorial 02 — AllGather methods (reference 02-intra-node-allgather.rst).

Three kernels (one-shot push, unidirectional ring, bidirectional ring) and
the size-based auto-selection; golden vs jax.lax.all_gather.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.comm import AllGatherMethod, all_gather


def main():
    mesh = mesh_lib.tp_mesh(8)
    x = jax.random.normal(jax.random.key(0), (8 * 32, 256), jnp.float32)
    xs = mesh_lib.shard(mesh, x, "tp", None)
    for method in (AllGatherMethod.PUSH_1SHOT, AllGatherMethod.RING_1D,
                   AllGatherMethod.RING_BIDIR, AllGatherMethod.AUTO):
        out = all_gather(xs, mesh, method=method)
        np.testing.assert_allclose(np.asarray(jax.device_get(out)),
                                   np.asarray(x))
        print(f"{method.value:12s} OK")


if __name__ == "__main__":
    main()
