"""Tutorial 02 — the AllGather family (reference 02-intra-node-allgather.rst).

Tutorial 01 hand-rolled a ONE-SHOT AllGather: every rank pushes its block
to every peer, n-1 messages per link in one latency hop.  This tutorial
adds the other two members of the family and the reasoning that picks
between them:

* **PUSH_1SHOT** — all-to-all push.  Per rank: ``(n-1) * nbytes`` sent,
  ONE hop of latency.  Wins while messages are small enough that hop
  latency, not wire time, dominates.
* **RING_1D** — n-1 steps; at step s each rank forwards the chunk it
  received at step s-1 to its right neighbor.  Per rank: the same
  ``(n-1) * nbytes`` sent — but each LINK only ever carries each chunk
  once and all links run concurrently, so aggregate wire time is one
  chunk per step, at the cost of n-1 latency-chained hops.  Wins for
  large payloads.
* **RING_BIDIR** — two counter-rotating rings, each carrying half of
  every chunk: halves the number of serial hops for the same total wire
  bytes on a bidirectional ICI torus.

The reference reaches the same three shapes on NVLink (its
``allgather.py:46-601``); here the wire is the ICI torus and the kernels
are ``comm/allgather.py``.  Below you will:

1. write a minimal RING kernel inline (one ``remote_copy`` per step,
   with the forward-what-just-arrived dependency made explicit),
2. check it and all three production methods against
   ``jax.lax.all_gather``-equivalent replication,
3. read the latency/bandwidth crossover out of ``resolve_method`` and
   verify the auto-chosen method at both extremes.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import functools

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.comm import AllGatherMethod, all_gather
from triton_distributed_tpu.comm.allgather import resolve_method
from triton_distributed_tpu.core import compilation
from triton_distributed_tpu.lang import primitives as dl
from triton_distributed_tpu.lang.primitives import Team

N = 8
BLOCK = (8, 128)


# ---------------------------------------------------------------------------
# A minimal unidirectional ring AllGather.  The production kernel
# (comm/allgather.py RING_1D) adds chunked double-buffering and ACK
# credits; this one keeps only the essential dependency structure:
#
#   step 0: send MY block right;            wait for left's block
#   step s: send the block I got at s-1;    wait for the next arrival
#
# Every rank talks only to its two neighbors — that is what makes the
# ring the bandwidth shape on a torus: no link ever carries any chunk
# twice.


def ring_ag_kernel(team, x_ref, out_ref, send_sem, recv_sems):
    me, n = team.rank(), team.size
    rows = x_ref.shape[0]

    # own block lands in slot[me] (local DMA; completes before the sends
    # below may forward it at step 0)
    def own_copy(sem):
        dl.local_copy(x_ref, out_ref.at[pl.ds(me * rows, rows)], sem).wait()

    pl.run_scoped(own_copy, pltpu.SemaphoreType.DMA)
    dl.collective_prologue(team, neighbors_only=True)
    _, right = team.neighbor_ranks()
    right_id = team.device_id(right)
    for s in range(n - 1):
        # the chunk that entered MY slot table most recently: my own block
        # at step 0, the step s-1 arrival after that — its origin is rank
        # (me - s) mod n, and it goes to the SAME slot on my right
        # neighbor, so the slice is identical on both sides of the copy
        src = jax.lax.rem(me + jnp.int32(n - s), jnp.int32(n))
        src_slot = out_ref.at[pl.ds(src * rows, rows)]
        dl.remote_copy(src_slot, src_slot, send_sem, recv_sems.at[s],
                       right_id)
        # this step's arrival from the LEFT must land before the next
        # iteration forwards it (recv_sems[s] counts exactly one block)
        arrived = jax.lax.rem(me + jnp.int32(n - s - 1), jnp.int32(n))
        dl.wait_recv(out_ref.at[pl.ds(arrived * rows, rows)],
                     recv_sems.at[s])
    # balance the n-1 outgoing sends (tutorial 01, rule 3)
    for _ in range(n - 1):
        dl.wait_send(x_ref, send_sem)


def build_ring(team):
    call = pl.pallas_call(
        functools.partial(ring_ag_kernel, team),
        out_shape=jax.ShapeDtypeStruct((N * BLOCK[0], BLOCK[1]), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA((N - 1,))],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("tutorial"),
        ),
        interpret=compilation.interpret_mode(),
    )
    mesh = mesh_lib.tp_mesh(N)
    return compilation.jit_shard_map(
        call, mesh, in_specs=P("tp", None), out_specs=P("tp", None)
    )


def main():
    mesh = mesh_lib.tp_mesh(N)
    team = Team.of(mesh, "tp")
    x = jax.random.normal(jax.random.key(0), (N * BLOCK[0], BLOCK[1]),
                          jnp.float32)
    xs = mesh_lib.shard(mesh, x, "tp", None)

    # 1. the inline ring kernel: every rank's copy equals the full input
    fn = build_ring(team)
    out = np.asarray(jax.device_get(fn(xs))).reshape(N, N * BLOCK[0],
                                                     BLOCK[1])
    for r in range(N):
        np.testing.assert_allclose(out[r], np.asarray(x), atol=0, rtol=0)
    print("inline ring AllGather == full input on every rank     OK")

    # 2. the three production methods + AUTO against the same golden
    for method in (AllGatherMethod.PUSH_1SHOT, AllGatherMethod.RING_1D,
                   AllGatherMethod.RING_BIDIR, AllGatherMethod.AUTO):
        got = all_gather(xs, mesh, method=method)
        np.testing.assert_allclose(np.asarray(jax.device_get(got)),
                                   np.asarray(x))
        print(f"comm.all_gather {method.value:12s} == replicated x    OK")

    # 3. the crossover: AUTO resolves from per-shard bytes.  A few-KB
    # decode activation wants the one-hop push; a hundreds-MB prefill
    # gather wants a ring (thresholds measured on-chip; see
    # comm/allgather.py).
    small = resolve_method(AllGatherMethod.AUTO, (8, 128), jnp.bfloat16, N)
    large = resolve_method(AllGatherMethod.AUTO, (16384, 8192), jnp.bfloat16,
                           N)
    print(f"auto-select: 2 KiB shard -> {small.value}, "
          f"256 MiB shard -> {large.value}")
    assert small == AllGatherMethod.PUSH_1SHOT
    assert large in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR)
    print("\nNext: 03 lifts the ring onto a two-level ICI x DCN mesh; 07 "
          "fuses it INTO a matmul so the wire hides behind the MXU.")


if __name__ == "__main__":
    main()
