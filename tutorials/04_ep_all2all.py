"""Tutorial 04 — MoE EP All-to-All dispatch/combine (reference
04-deepseek-infer-all2all.rst).

Expert parallelism's data movement problem: every rank holds T tokens,
each routed to one of n*epr experts, and expert e lives on rank
e // epr.  Tokens must travel to their expert's rank (DISPATCH), be
transformed there, and travel home into their ORIGINAL slots (COMBINE).
Counts are data-dependent — rank r cannot know how many tokens rank s
will send it until runtime.

The reference solves this with NVSHMEM: each rank pushes its tokens
into pre-agreed LANDING ZONES in every peer's symmetric heap, so no
receiver-side bookkeeping is needed mid-flight
(``low_latency_all_to_all.py:36-120``).  The TPU translation
(``comm/all_to_all.py``):

* **Variable length = a traced count of fixed-shape chunk DMAs.**  A
  remote DMA needs a static shape, so each rank's sends are cut into
  ``chunk``-row pieces and a ``fori_loop`` issues ceil(count/chunk)
  copies.  The zone is sized for the worst case (every token to one
  peer) — wire traffic follows the REAL counts; only zone memory pays
  worst case.
* **Zones by source rank.**  Rank r's receive buffer is n slabs of Z
  rows; slab s holds whatever rank s sent, already grouped by r's local
  experts (the sort order guarantees it).  Like the reference, arrival
  needs no re-bucketing.
* **The split table rides ``lax.all_to_all``** — a tiny dense exchange
  whose latency hides under the payload DMAs.

Below you will:

1. build the zone layout's GOLDEN MODEL inline (pure numpy: who lands
   where, in what order) and check ``ep_dispatch``'s output against it
   slab by slab;
2. run expert compute in the zones and ``ep_combine`` home, checking the
   original order is restored exactly;
3. differentiate through the round trip — dispatch and combine are each
   other's adjoints, so gradients flow across the A2A at full precision
   (the reference is inference-only here).
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import AllToAllConfig, ep_combine, ep_dispatch

N = 8           # ep ranks
T = 32          # tokens per rank (static worst case)
H = 128         # hidden
EPR = 2         # experts per rank -> E = N * EPR experts total


def golden_zones(xs, sps):
    """Pure-numpy model of the dispatch: for destination rank r and
    source rank s, the tokens of s routed to r's experts, in s's
    sorted-by-expert order.  This IS the zone contract ``ep_dispatch``
    promises; everything else in the kernel is transport."""
    zones = {}
    for r in range(N):
        lo, hi = r * EPR, (r + 1) * EPR
        for s in range(N):
            bounds = np.concatenate([[0], np.cumsum(sps[s])])
            rows = [xs[s][bounds[e]:bounds[e + 1]] for e in range(lo, hi)]
            zones[r, s] = np.concatenate(rows) if rows else np.zeros((0, H))
    return zones


def main():
    mesh = mesh_lib.make_mesh({"ep": N}, devices=jax.devices()[:N])
    rng = np.random.default_rng(0)
    xs, sps = [], []
    for r in range(N):
        w = rng.random(N * EPR)
        split = np.floor(w / w.sum() * T).astype(np.int32)
        split[0] += T - split.sum()          # exactly T routed rows
        xs.append(rng.standard_normal((T, H)).astype(np.float32))
        sps.append(split)
    x = jnp.asarray(np.concatenate(xs))                  # (N*T, H)
    splits = jnp.asarray(np.concatenate(sps))            # (N * N*EPR,)
    xd = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
    sd = jax.device_put(splits, NamedSharding(mesh, P("ep")))
    cfg = AllToAllConfig(chunk=8)

    # 1. dispatch, then hold the kernel to the golden zone contract
    recv, recv_splits = ep_dispatch(xd, sd, mesh, "ep", config=cfg)
    z = recv.shape[1]
    print(f"zones: {recv.shape} (Z={z} worst-case rows), "
          f"splits table {recv_splits.shape}")
    gold = golden_zones(xs, sps)
    recv_np = np.asarray(jax.device_get(recv)).reshape(N, N, z, H)
    rs_np = np.asarray(jax.device_get(recv_splits)).reshape(N, N, EPR)
    for r in range(N):
        for s in range(N):
            want = gold[r, s]
            assert rs_np[r, s].sum() == len(want)        # counts agree
            np.testing.assert_allclose(recv_np[r, s, :len(want)], want)
    print("every landing zone matches the golden permutation     OK")

    # 2. expert compute in place (here: x2), combine home, order restored
    back = ep_combine(recv * 2.0, sd, mesh, "ep", token_dim=T, config=cfg)
    np.testing.assert_allclose(np.asarray(jax.device_get(back)),
                               np.asarray(x) * 2.0)
    print("dispatch -> expert(x2) -> combine == original order    OK")

    # 3. gradients across the wire: combine is dispatch's adjoint, so
    # d(loss)/d(x) of the round trip equals the direct gradient
    def loss(x_):
        recv_, _ = ep_dispatch(x_, sd, mesh, "ep", config=cfg)
        out = ep_combine(recv_ * 3.0, sd, mesh, "ep", token_dim=T,
                         config=cfg)
        return (out ** 2).sum()

    g = jax.grad(loss)(xd)
    # round trip is x -> 3x, so d/dx sum((3x)^2) = 18x
    np.testing.assert_allclose(np.asarray(jax.device_get(g)),
                               18.0 * np.asarray(x), rtol=1e-5)
    print("grad through dispatch/combine == 18x (adjoint pair)    OK")
    print("\nNext: 11 builds the full MoE layer on these two ops (top-k "
          "routing, fp8 wire payloads); 12 trains through it.")


if __name__ == "__main__":
    main()
