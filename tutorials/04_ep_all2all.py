"""Tutorial 04 — MoE EP All-to-All dispatch/combine (reference
04-deepseek-infer-all2all.rst).

Tokens sorted by expert travel to their expert-owner ranks as chunked
remote DMAs (split counts ride a tiny lax.all_to_all); after expert
compute they return to their origins in the original order.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import AllToAllConfig, ep_combine, ep_dispatch


def main():
    n, t, h, e = 8, 32, 128, 16
    mesh = mesh_lib.make_mesh({"ep": n}, devices=jax.devices()[:n])
    rng = np.random.default_rng(0)
    xs, sps = [], []
    for r in range(n):
        w = rng.random(e)
        split = np.floor(w / w.sum() * t).astype(np.int32)
        split[0] += t - split.sum()
        xs.append(rng.standard_normal((t, h)).astype(np.float32))
        sps.append(split)
    x = jnp.asarray(np.concatenate(xs))
    splits = jnp.asarray(np.concatenate(sps))
    xd = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
    sd = jax.device_put(splits, NamedSharding(mesh, P("ep")))
    cfg = AllToAllConfig(chunk=8)
    recv, recv_splits = ep_dispatch(xd, sd, mesh, "ep", config=cfg)
    print("dispatched zones:", recv.shape, "recv splits:", recv_splits.shape)
    back = ep_combine(recv * 2.0, sd, mesh, "ep", token_dim=t, config=cfg)
    np.testing.assert_allclose(np.asarray(jax.device_get(back)),
                               np.asarray(x) * 2.0)
    print("dispatch -> expert(x2) -> combine round trip OK")


if __name__ == "__main__":
    main()
