"""Tutorial 01 — the distributed primitive vocabulary (notify/wait/remote_copy).

Reference: 01-distributed-notify-wait.rst.  A hand-written Pallas kernel:
every rank pushes its block to its right neighbor and waits for the left
neighbor's block — the minimal signal/wait producer-consumer pattern all
the library kernels are built from.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import functools

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.core import compilation
from triton_distributed_tpu.lang import primitives as dl
from triton_distributed_tpu.lang.primitives import Team


def shift_kernel(team, x_ref, out_ref, send_sem, recv_sem):
    # 1. barrier before the first remote write (EVERY collective kernel)
    dl.collective_prologue(team, neighbors_only=True)
    # 2. push my block into my RIGHT neighbor's output...
    _, right = team.neighbor_ranks()
    dl.remote_copy(x_ref, out_ref, send_sem, recv_sem, team.device_id(right))
    # 3. ...and wait until my LEFT neighbor's block has landed in mine
    dl.wait_recv(out_ref, recv_sem)
    # 4. drain my own send so repeated calls start balanced
    dl.wait_send(x_ref, send_sem)


def main():
    mesh = mesh_lib.tp_mesh(8)
    team = Team.of(mesh, "tp")
    call = pl.pallas_call(
        functools.partial(shift_kernel, team),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())] * 2,
        compiler_params=compilation.compiler_params(
            collective=True, collective_id=compilation.collective_id("test")
        ),
        interpret=compilation.interpret_mode(),
    )
    fn = compilation.jit_shard_map(
        call, mesh, in_specs=P("tp", None), out_specs=P("tp", None)
    )
    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(64, 128)
    xs = mesh_lib.shard(mesh, x, "tp", None)
    out = jax.device_get(fn(xs))
    # rank r now holds rank r-1's block
    import numpy as np

    perm = np.array([7, 0, 1, 2, 3, 4, 5, 6])
    np.testing.assert_array_equal(out.reshape(8, 8, 128),
                                  np.asarray(x).reshape(8, 8, 128)[perm])
    print("ring shift via notify/wait OK")


if __name__ == "__main__":
    main()
