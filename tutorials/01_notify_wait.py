"""Tutorial 01 — the distributed primitive vocabulary.

Reference: 01-distributed-notify-wait.rst, which teaches NVSHMEM-style
``putmem_signal`` / ``signal_wait_until`` by hand-writing a producer-
consumer kernel.  This tutorial does the TPU-native equivalent: you will
write THREE kernels from scratch with ``triton_distributed_tpu.lang``,
each introducing one more primitive, ending with a complete hand-rolled
AllGather that you can check against ``jax.lax.all_gather``.

The vocabulary (see ``docs/primitives.md`` for the full semantics map):

====================  ====================================================
reference (NVSHMEM)   here
====================  ====================================================
``putmem_signal``     ``dl.remote_copy(src, dst, send_sem, recv_sem, id)``
``signal_wait_until`` ``dl.wait_recv(ref, sem)`` / ``dl.wait(sem, n)``
``signal_op(ADD)``    ``dl.notify(sem, device_id, inc=...)``
``nvshmem_my_pe``     ``dl.rank(axis)`` / ``Team.rank()``
``nvshmem_ptr``       logical device ids — ``Team.device_id(rank)``
``barrier_all``       ``dl.collective_prologue`` / ``dl.barrier_all``
====================  ====================================================

Three rules carry over from the reference's programming model:

1. **Barrier before the first remote write.**  A remote DMA may land in
   a peer's buffer before that peer has entered the kernel — on hardware
   the buffer may still be in use by the peer's PREVIOUS computation.
   Every collective kernel opens with ``dl.collective_prologue``.
2. **Counting, not flag values.**  TPU semaphores count.  The
   reference's "wait until flag == 42" protocols are re-expressed as
   "wait for N arrivals" — and a DMA's completion semaphore counts the
   transfer itself, so data arrival needs no separate flag at all.
3. **Balance every semaphore.**  Each ``remote_copy`` leaves one count
   on the sender's ``send_sem`` and one on the receiver's ``recv_sem``;
   each must be consumed exactly once (``wait_send`` / ``wait_recv``) or
   the NEXT invocation of the kernel inherits the residue.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import functools

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.core import compilation
from triton_distributed_tpu.lang import primitives as dl
from triton_distributed_tpu.lang.primitives import Team

N = 8
BLOCK = (8, 128)   # sublane x lane granule: keep the last dim at 128


def _build(team, kernel, out_rows, scratch_shapes):
    """Boilerplate shared by the three kernels: a pallas_call under
    shard_map over the tp axis.  ``collective_id`` keys the global barrier
    semaphore — CONCURRENT collectives must not share a family, but these
    kernels run sequentially, so they share the registered "tutorial" id
    (counting barriers leave no residue between launches)."""
    call = pl.pallas_call(
        functools.partial(kernel, team),
        out_shape=jax.ShapeDtypeStruct((out_rows, BLOCK[1]), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch_shapes,
        compiler_params=compilation.compiler_params(
            collective=True, collective_id=compilation.collective_id("tutorial")
        ),
        interpret=compilation.interpret_mode(),
    )
    mesh = mesh_lib.tp_mesh(N)
    return compilation.jit_shard_map(
        call, mesh, in_specs=P("tp", None), out_specs=P("tp", None)
    )


# ---------------------------------------------------------------------------
# Kernel 1: ring shift — one remote_copy, the smallest possible collective


def shift_kernel(team, x_ref, out_ref, send_sem, recv_sem):
    # (rule 1) neighbors_only suffices: only ring neighbors write to us
    dl.collective_prologue(team, neighbors_only=True)
    # push my block into my RIGHT neighbor's out_ref.  The DMA is
    # addressed by LOGICAL device id: team.device_id translates a
    # tp-axis rank into the mesh-wide id (on a multi-axis mesh they
    # differ — see Team's docstring).
    _, right = team.neighbor_ranks()
    dl.remote_copy(x_ref, out_ref, send_sem, recv_sem, team.device_id(right))
    # (rule 2) the receive IS the signal: waiting on recv_sem for one
    # out_ref-shaped transfer blocks until my LEFT neighbor's push landed
    dl.wait_recv(out_ref, recv_sem)
    # (rule 3) drain my own send so repeated calls start balanced
    dl.wait_send(x_ref, send_sem)


# ---------------------------------------------------------------------------
# Kernel 2: notify/wait — decoupled signaling (the producer-consumer
# pattern).  Data moves as in kernel 1, but the CONSUMER only proceeds
# once the producer raises an application-level semaphore — the shape of
# every "tile ready" protocol in the fused ops (ops/ag_gemm.py waits
# per-chunk exactly like this).


def handshake_kernel(team, x_ref, out_ref, ready, send_sem, recv_sem):
    dl.collective_prologue(team, neighbors_only=True)
    _, right = team.neighbor_ranks()
    copy = dl.remote_copy(x_ref, out_ref, send_sem, recv_sem,
                          team.device_id(right))
    copy.wait()                          # both sems of MY transfer consumed
    # application-level signal: "your input is ready" (counting ADD)
    dl.notify(ready, team.device_id(right), inc=1)
    # consumer side: block until MY producer says go, then transform
    dl.wait(ready, 1)

    def scale(scratch, sem):
        dl.local_copy(out_ref, scratch, sem).wait()
        scratch[:] = scratch[:] * 2.0
        dl.local_copy(scratch, out_ref, sem).wait()

    pl.run_scoped(scale, pltpu.VMEM(BLOCK, jnp.float32),
                  pltpu.SemaphoreType.DMA)


# ---------------------------------------------------------------------------
# Kernel 3: a complete one-shot AllGather, hand-rolled.  Every rank
# pushes its block to EVERY peer's slot[me]; per-source recv semaphores
# tell each rank when each slot is live.  This is precisely
# comm/allgather.py's PUSH_1SHOT method, minus its production niceties —
# after this kernel, that file should read like your own code.


def all_gather_kernel(team, x_ref, out_ref, local_sem, send_sem, recv_sems):
    me, n = team.rank(), team.size
    rows = x_ref.shape[0]
    # own block into its slot (async local DMA; overlaps the barrier)
    own = dl.local_copy(x_ref, out_ref.at[pl.ds(me * rows, rows)], local_sem)
    dl.collective_prologue(team)         # full barrier: everyone writes us
    # push to every peer, staggered so the ring links aren't hot-spotted
    for off in range(1, n):
        dst = jax.lax.rem(me + off, n)
        dl.remote_copy(
            x_ref, out_ref.at[pl.ds(me * rows, rows)],
            send_sem, recv_sems.at[me], team.device_id(dst),
        )
    own.wait()
    # per-source arrival: slot p is live once ITS semaphore counts one
    # x-shaped transfer (rule 2: no flags — the DMA itself signals)
    for p in range(n):

        @pl.when(jnp.int32(p) != me)
        def _(p=p):
            dl.wait_recv(out_ref.at[pl.ds(p * rows, rows)], recv_sems.at[p])

    # (rule 3) n-1 outgoing sends to drain
    for _ in range(n - 1):
        dl.wait_send(x_ref, send_sem)


def main():
    mesh = mesh_lib.tp_mesh(N)
    team = Team.of(mesh, "tp")
    x = jnp.arange(N * BLOCK[0] * BLOCK[1], dtype=jnp.float32).reshape(
        N * BLOCK[0], BLOCK[1]
    )
    xs = mesh_lib.shard(mesh, x, "tp", None)
    xr = np.asarray(x).reshape(N, *BLOCK)

    # 1. ring shift: rank r ends with rank r-1's block
    fn = _build(team, shift_kernel, BLOCK[0],
                [pltpu.SemaphoreType.DMA(())] * 2)
    out = np.asarray(jax.device_get(fn(xs))).reshape(N, *BLOCK)
    np.testing.assert_array_equal(out, xr[np.r_[N - 1, 0:N - 1]])
    print("1. ring shift (remote_copy + wait_recv/wait_send)     OK")

    # 2. handshake: shifted AND doubled, gated by notify/wait
    fn = _build(
        team, handshake_kernel, BLOCK[0],
        [pltpu.SemaphoreType.REGULAR, pltpu.SemaphoreType.DMA(()),
         pltpu.SemaphoreType.DMA(())],
    )
    out = np.asarray(jax.device_get(fn(xs))).reshape(N, *BLOCK)
    np.testing.assert_array_equal(out, 2.0 * xr[np.r_[N - 1, 0:N - 1]])
    print("2. producer-consumer handshake (notify/wait)          OK")

    # 3. hand-rolled AllGather: replicated output == the whole input, and
    # identical to the XLA collective
    fn = _build(
        team, all_gather_kernel, N * BLOCK[0],
        [pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(()),
         pltpu.SemaphoreType.DMA((N,))],
    )
    # out_specs P("tp") stacks each device's replicated copy: every one of
    # the N copies must be the whole of x
    out = np.asarray(jax.device_get(fn(xs))).reshape(N, N * BLOCK[0], BLOCK[1])
    for r in range(N):
        np.testing.assert_array_equal(out[r], np.asarray(x))
    print("3. hand-rolled one-shot AllGather == lax.all_gather   OK")
    print("\nNext: tutorials 02-06 use the production comm/ kernels these "
          "patterns grow into; 07-08 fuse them INTO matmuls.")


if __name__ == "__main__":
    main()
