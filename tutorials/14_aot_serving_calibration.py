"""Tutorial 14 — zero-compile serving and measured infrastructure.

Three round-5 capabilities that turn the framework's measurements into
product behavior:

1. **Bucketed AOT serving** (reference ``tools/compile_aot.py:61-130``
   signature spaces + the ``link_all`` dispatcher).  A serving process
   must never trace or compile: ``Engine.precompile(buckets)``
   AOT-compiles prefill for a prompt-length shape space plus the decode
   step.  At serve time a prompt right-pads to the smallest bucket >=
   its length and passes its TRUE length as a traced scalar — causal
   attention never lets pad positions influence earlier logits, and the
   cache length masks the garbage K/V the pads wrote, so ONE bucket
   executable is exact for every length it covers.  On real hardware
   the bundle serializes next to the weights and a second process
   serves through the deserialized executables with zero retraces.

2. **Measured link calibration** (reference NIC/NVLink probes,
   ``comm_perf_model.py:92-129``).  The AG push-vs-ring and AR
   one-shot-vs-two-shot crossovers are bandwidth-delay products — a
   LINK property, not a constant.  ``tools/calibrate.py`` measures each
   wire class once (size-swept ppermute, linear fit t = L + S/bw),
   persists the result, and ``choose_method`` derives its thresholds
   from it; without a calibration the documented cold-start constants
   hold.

3. **Measured overlap** (reference hardware charts,
   ``asset/ag-gemm-intra-node.png``).  ``tools/overlap.py`` decomposes
   the tile pipeline into fused / dma-only / mxu-only probe kernels
   over identical grids: if the pipeline overlaps, the fused time sits
   at max(phases), not their sum.  The on-chip captures read 0.76-0.94
   of the DMA stream hidden under compute.
"""

from common import bootstrap

jax, mesh_lib = bootstrap()

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import Engine, ModelConfig

N = 8
CFG = ModelConfig(
    num_layers=1, hidden=128, intermediate=256, num_heads=8, num_kv_heads=8,
    head_dim=32, vocab=256, max_length=64, dtype=jnp.float32,
)


def main():
    import os
    import tempfile

    # hermetic calibration: the planted tutorial numbers must NEVER touch
    # a real persisted calibration (an operator's TDT_LINKCAL_CACHE or
    # the default ~/.cache path) — point the cache at a throwaway file
    # unconditionally for the rest of this process
    os.environ["TDT_LINKCAL_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="tutorial14-"), "linkcal.json"
    )
    from triton_distributed_tpu.tools import calibrate as _cal

    _cal.invalidate_cache()

    mesh = mesh_lib.tp_mesh(N)

    # -- 1. bucketed AOT serving ------------------------------------------
    eng = Engine.build(CFG, mesh, key=jax.random.key(0), batch=2)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab, (2, 8)), jnp.int32
    )
    ref = np.asarray(eng.generate(ids, 4))

    manifest = eng.precompile([16, 32])
    print("precompiled buckets:", manifest["buckets"])
    got = np.asarray(eng.generate(ids, 4))     # pads 8 -> bucket 16
    assert (got == ref).all(), "bucketed serving must be EXACT"
    print("bucketed generation matches the unbucketed path exactly")
    # lengths the raw path cannot even run (tokens % tp != 0) now serve:
    odd = jnp.asarray(
        np.random.default_rng(1).integers(0, CFG.vocab, (2, 9)), jnp.int32
    )
    print("length-9 prompt served via bucket 16:",
          np.asarray(eng.generate(odd, 3)).shape)
    # (on real hardware: eng.precompile([...], save_dir="...") then a
    # second process Engine.build(...).load_precompiled("...") serves
    # with zero retraces — scripts/run_hw_markers.py proves it on-chip;
    # interpret-mode kernels embed python callbacks XLA cannot
    # serialize, so this tutorial stays in-process.)

    # -- 2. link calibration feeding method choice ------------------------
    from triton_distributed_tpu.comm.allgather import (
        AllGatherMethod, choose_method,
    )
    from triton_distributed_tpu.tools import calibrate as cal

    probe = 1 << 20  # a 1 MiB shard
    print("cold-start method for 1 MiB:", choose_method(probe, N).value)
    # a measured high-latency link stretches the push window past 1 MiB
    cal.save_calibration(cal.LinkCalibration(
        ici_gbps=186.0, ici_hop_us=10.0, device_kind="tutorial",
        n_devices=N,
    ))
    print("calibrated (10 us hops) method for 1 MiB:",
          choose_method(probe, N).value,
          f"(threshold {cal.push_bytes_threshold()} B = measured BDP)")
    assert choose_method(probe, N) == AllGatherMethod.PUSH_1SHOT

    # -- 3. measured overlap ----------------------------------------------
    from triton_distributed_tpu.tools.overlap import hidden_pct, overlap_kernels

    fused, dma, mxu = overlap_kernels(256, 256, 256, bm=128, bn=128,
                                      bk=128, dtype=jnp.float32)
    a = jax.random.normal(jax.random.key(2), (256, 256), jnp.float32)
    b = jax.random.normal(jax.random.key(3), (256, 256), jnp.float32)
    assert jnp.allclose(fused(a, b), a @ b, atol=2e-3)
    print("overlap probes: fused kernel IS the real matmul; on-chip the",
          "three wall times give overlap_hidden_pct (bench.py overlap)")
    print("hidden_pct(fused=1.0, dma=0.6, mxu=1.0) =",
          hidden_pct(1.0, 0.6, 1.0), "(fused == max -> fully hidden)")


if __name__ == "__main__":
    main()
    print("tutorial 14 ok")
