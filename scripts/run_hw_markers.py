#!/usr/bin/env python
"""Run the hardware-only test cases on the real TPU.

The pytest suite forces a virtual CPU mesh (tests/conftest.py), which
cannot execute primitives with no interpret-mode rule — today that is
``lang.peek`` (semaphore_read).  This runner executes those cases
directly on the attached chip, outside pytest so the conftest CPU
forcing never engages.  Run it wherever ``jax.devices()`` shows a TPU:

    python scripts/run_hw_markers.py

Exit 0 = every hardware marker passed.
"""

import importlib
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HW_CASES = [
    ("tests.test_primitives_matrix", "test_peek_reads_count_on_hardware"),
    # AOT bundle serialize/reload: interpret kernels embed python
    # callbacks XLA cannot serialize, so the second-process-zero-retrace
    # proof only runs against real Mosaic lowering
    ("tests.test_engine_aot", "test_second_process_serves_with_zero_retraces"),
]


def main() -> int:
    import jax

    kinds = {d.platform for d in jax.devices()}
    if kinds == {"cpu"}:
        print("no accelerator attached — hardware markers need a real TPU")
        return 1
    failed = 0
    for mod_name, fn_name in HW_CASES:
        fn = getattr(importlib.import_module(mod_name), fn_name)
        try:
            fn()
            print(f"PASS {mod_name}::{fn_name}")
        except Exception as exc:  # noqa: BLE001
            failed += 1
            print(f"FAIL {mod_name}::{fn_name}: {exc!r}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
