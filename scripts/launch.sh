#!/usr/bin/env bash
# Launch wrapper (reference: scripts/launch.sh -- the torchrun/nvshmem
# bootstrap). On TPU the rendezvous is jax.distributed.initialize, driven
# by three env vars; this script fills them for the common cases.
#
#   scripts/launch.sh sim 8 tutorials/07_ag_gemm.py   # virtual CPU mesh
#   scripts/launch.sh pod <coordinator:port> <num_procs> <proc_id> prog.py
#
# Multi-host TPU pods: run this once per host with the same coordinator
# address and per-host process ids (your scheduler usually sets these).
set -euo pipefail

mode="${1:?usage: launch.sh sim|pod ...}"
shift
case "$mode" in
  sim)
    n="${1:?sim needs a device count}"
    shift
    # +2 spares: interpret-mode kernels need free client threads
    export TDT_SIM_DEVICES="$n"
    exec python -c "
from triton_distributed_tpu.core.platform import force_cpu, SPARE_VIRTUAL_DEVICES
import os, runpy, sys
force_cpu(int(os.environ['TDT_SIM_DEVICES']) + SPARE_VIRTUAL_DEVICES)
sys.argv = sys.argv[1:]
sys.path.insert(0, os.path.dirname(os.path.abspath(sys.argv[0])))
runpy.run_path(sys.argv[0], run_name='__main__')
" "$@"
    ;;
  pod)
    export COORDINATOR_ADDRESS="${1:?pod needs coordinator host:port}"
    export NUM_PROCESSES="${2:?pod needs process count}"
    export PROCESS_ID="${3:?pod needs the local process id}"
    shift 3
    exec python "$@"
    ;;
  *)
    echo "unknown mode: $mode (use sim|pod)" >&2
    exit 2
    ;;
esac
