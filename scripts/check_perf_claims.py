#!/usr/bin/env python
"""Fail when documented perf claims drift from the newest driver record.

Round-3 found docs quoting ratios the driver record contradicted; round-4
closed that loop with a machine-readable registry of RATIO ranges — and
its first driver capture promptly exposed the flaw in gating on ratios:
the XLA baselines swing 2-3x with chip state (docs/perf.md), so a
single capture's ratio is a draw from a wide spread, and widening the
claimed ranges to cover the spread made them unfalsifiable (a lower
bound below 1.0 "claims" we might lose).  Worse, mixing the slope
absolute with the raw-window ratio implied a 1,062 GB/s decode baseline
on an 819 GB/s HBM part and the gate accepted it.

Round-5 restructure (VERDICT r4 next #1):

- **PRIMARY claims are absolute throughput floors** on OUR kernel's
  recorded ``value`` — the quantity that is stable across chip states.
  A capture below the floor fails the gate: that is a regression (or a
  measurement protocol break), never "XLA had a good day".
- **Physical ceilings** reject impossible measurements: ``value`` and
  ``baseline_value`` (both slope absolutes, same estimator) must sit
  below the chip's peak for their bound resource.  A 1,062 GB/s decode
  baseline now fails the capture instead of passing the gate.
- **Ratio spreads are secondary and informational**: ``vs_baseline`` is
  checked against the documented observed spread and drift prints a
  WARNING (visible in CI logs) without failing the run — a ratio
  against an unstable baseline is evidence, not a claim.  Deterministic
  ratios (byte accounting) remain hard failures: they have no noise.

Usage: python scripts/check_perf_claims.py [repo_root] [--trend]
Exit 0 = every recorded metric with a claim satisfies its primary
claims.  Ratio-spread drift warns on stdout but does not fail.
``--trend`` additionally prints the round-over-round trajectory
warnings (``triton_distributed_tpu.obs.history`` via
``scripts/bench_history.py``) next to the floor verdicts — monotonic
declines and below-band draws are visible in the same gate output
before a floor ever breaks; they never change the exit code.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

# v5e physical context for the ceilings: ~197 TFLOP/s bf16 MXU peak and
# ~819 GB/s HBM.  Ceilings admit the slope estimator's documented noise
# on a legitimate near-peak measurement (decode slope absolutes have
# read up to ~890 GB/s on the 819 GB/s part — ~9% high) while still
# rejecting the 1.3x-of-peak class of artifact.
_MXU_CEIL_TFLOPS = 210.0
_HBM_CEIL_GBPS = 925.0

# metric-name prefix -> claim dict.  Keys:
#   floor            PRIMARY: recorded ``value`` must be >= this (hard)
#   value_ceiling    ``value`` above this is a suspect capture (hard)
#   value_max        upper bound for lower-is-better values (hard)
#   baseline_ceiling ``baseline_value`` above this is impossible (hard)
#   ratio_spread     (lo, hi) documented observed vs_baseline spread
#                    (SECONDARY: drift prints a warning, exit stays 0)
#   exact_ratio      (lo, hi, band) deterministic vs_baseline (hard)
#   since            first round the claim binds to
#   min_devices      claim binds only to records captured on >= this many
#                    devices (slice-gated claims: the record's "devices"
#                    field; absent = 1).  Completeness likewise requires
#                    the metric only when the sweep sentinel's "devices"
#                    reaches the bar — a single-chip sweep cannot MISS a
#                    slice-only metric.
#   slice_ratio_floor vs_baseline floor that is HARD on multi-device
#                    records only (devices > 1): the distributed ratio
#                    the reference claims, unfalsifiable at tp=1 where
#                    the ratio is definitional parity
#
# Floors are set just BELOW the multi-round observed MINIMA of our
# kernels' absolutes across chip states (the docs/perf.md observed
# column; BENCH_r01-r04 + round-5 session sweeps): they assert "our
# kernel never does worse than this on a healthy chip" — a lower bound
# that can actually fail — while a capture in a throttled-but-normal
# chip state documented before round 5 must not trip them.
CLAIMS = {
    "single_chip_gemm_7168_bf16": {
        "floor": 140.0, "value_ceiling": _MXU_CEIL_TFLOPS,
        "baseline_ceiling": _MXU_CEIL_TFLOPS,
        "ratio_spread": (0.95, 1.15), "since": 4,
    },
    "single_chip_gemm_m4096_n4096_k4096_bf16": {
        "floor": 140.0, "value_ceiling": _MXU_CEIL_TFLOPS,
        "baseline_ceiling": _MXU_CEIL_TFLOPS,
        "ratio_spread": (0.95, 4.0), "since": 4,
    },
    "single_chip_gemm_m8192_n2048_k7168_bf16": {
        "floor": 115.0, "value_ceiling": _MXU_CEIL_TFLOPS,
        "baseline_ceiling": _MXU_CEIL_TFLOPS,
        "ratio_spread": (0.90, 1.60), "since": 4,
    },
    # the prefill flash kernel is VPU(softmax)-bound at ~95 TF/s in fast
    # states, ~65 in degraded ones (docs/perf.md roofline); the unfused
    # baseline does 2x the counted useful flops, so its useful-work
    # ceiling is ~half the MXU peak.  Floor ratcheted 42 -> 60 in round 6
    # with decode's dip-margin methodology (VERDICT r5 weak #3): the
    # committed-round trajectory (r03 71.5, r04 67.4, r05 88.5 —
    # `scripts/bench_history.py --metric flash`) bottoms at 67.4, and the
    # 44-50 TF/s draws in docs/perf.md's observed range were pre-round-4
    # session sweeps of the NaN-guard-era kernel plus whole-chip throttle
    # dips the symmetric retry now catches; 60 sits ~11% under the
    # committed minimum while failing any regression toward the old
    # 44-50 band (docs/perf.md "Flash floor ratchet")
    "flash_attn_b1_h32_s4096_d128": {
        "floor": 60.0, "value_ceiling": 115.0, "baseline_ceiling": 110.0,
        "ratio_spread": (2.5, 13.0), "since": 4,
    },
    # both engines are KV-bandwidth bound: absolutes are GB/s of cache
    # read and CANNOT exceed HBM.  With the (1, 2048) streaming geometry
    # the round-5 full-protocol captures read 678-890 GB/s across the
    # day's chip states (the 678 draw landed in a throttled phase; the
    # healthy band is 708-890); 650 sits ~4% below the observed minimum
    # while still failing any regression toward the old (4, 512)
    # geometry's 540-620 GB/s band
    "decode_attn_b8_h32_hk8_s8192_d128": {
        "floor": 650.0, "value_ceiling": _HBM_CEIL_GBPS,
        "baseline_ceiling": _HBM_CEIL_GBPS,
        "ratio_spread": (0.65, 1.40), "since": 5,
    },
    # grouped draws: 154.7 (r04), 165-167 (round-5 healthy), 131.4 (one
    # whole-chip dip draw, aliased-XLA crown, recovered to 165 minutes
    # later).  125 sits below the dip draw while still failing a
    # regression to the pre-pad-elision kernel (~115, the r03 state)
    "group_gemm_t8192_k7168_n2048_e8": {
        "floor": 125.0, "value_ceiling": _MXU_CEIL_TFLOPS,
        "baseline_ceiling": _MXU_CEIL_TFLOPS,
        "ratio_spread": (0.90, 1.30), "since": 4,
    },
    "tp_mlp_m4096_k7168_i7168_tp1": {
        "floor": 145.0, "value_ceiling": _MXU_CEIL_TFLOPS,
        "baseline_ceiling": _MXU_CEIL_TFLOPS,
        "ratio_spread": (0.95, 1.30), "since": 4,
    },
    # ms/step is chip-state dependent (lower is better) — value_max is a
    # gross-regression tripwire, the ratio is definitional parity at tp=1
    # (accounting-only metric, VERDICT r4 weak #5; the distributed
    # property in this line is the wire-bytes fields).  The prefix is
    # tp-AGNOSTIC (bench.py emits ..._tp{ntp}_...): a multi-chip capture
    # must satisfy the same claim, not trip a spurious MISSING failure
    # (ADVICE r5 low #2)
    "qwen_decode_step_b128_tp": {
        "value_max": 20.0, "ratio_spread": (0.90, 1.35), "since": 4,
        # the decode-mode claim with teeth, armed for the first real
        # slice capture (VERDICT r5 next #7): on devices > 1 the psum/ar
        # ratio is a distributed measurement and the fast-AR path must
        # at least hold parity with XLA's psum (the reference claims
        # 1.27-1.37x; 0.95 is the never-lose floor that still fails a
        # genuinely slower AR path)
        "slice_ratio_floor": 0.95,
    },
    # fused AG-GEMM overlap on a real slice: the v5p >= 90%-hidden
    # BASELINE target, gated (not merely logged) from the first
    # multi-device capture on (VERDICT r5 next #7).  Keyed on the
    # record's "devices" field — a tp=1 run never emits this metric and
    # a single-chip sweep is exempt from its completeness check.
    "overlap_hidden_pct_ag_gemm": {
        "floor": 0.90, "value_max": 1.0, "min_devices": 2, "since": 6,
    },
    # byte accounting is deterministic: any drift is a payload-format
    # regression and must fail exactly
    "moe_ep_a2a_fp8_wire_bytes_h7168": {
        "floor": 7296, "value_max": 7296,
        "exact_ratio": (1.96, 1.97, 0.0), "since": 3,
    },
    # single-chip latency floor (8 KiB Pallas round-trip, tunneled
    # dispatch included): a gross-regression tripwire only — absolute
    # latency on this dev box is dominated by the tunnel RTT
    "latency_class_us": {"value_max": 2000.0, "since": 5},
    # continuous-batching serving SLOs (ISSUE 6; `bench.py serve` — a
    # seeded open-loop trace overcommitting the KV-page budget ~2x
    # through the scheduler).  Round 6 ESTABLISHES the record lines so
    # obs.history trends them; the p99 bound is a gross tripwire only
    # (TTFT under deliberate saturation includes queue wait) and the
    # throughput floor grows once committed rounds establish a band —
    # the sim-backend fallback marks records `interpret`, so hard
    # claims bind only to real-engine captures
    "serve_ttft_ms_p99": {"value_max": 30_000.0, "since": 6},
    # floor 1 tok/s = "the scheduler completed SOMETHING": a crash-level
    # tripwire until committed rounds establish a real band to ratchet
    "serve_tokens_per_s_saturated": {"floor": 1.0, "since": 6},
    # the TDT_INTEGRITY verification tax on AG/RS at the tuned configs
    # (ISSUE 7; `bench.py integrity`).  warn_max is ADVISORY — a drift
    # past 5% is a trend finding for obs.history, not a build breaker;
    # value_max is the gross tripwire (a verification layer that
    # DOUBLES the op on a real slice is broken, not taxed).  CPU-
    # container captures are host-modeled and marked `interpret`
    # (never hard-gated)
    "integrity_overhead_pct": {"warn_max": 5.0, "value_max": 100.0,
                               "since": 7},
    # decode megakernel (ISSUE 8; `bench.py decode` / `auto`).  The
    # dispatch count is STATIC (traced-jaxpr accounting,
    # ops.fused_decode.count_decode_dispatches): on a slice the fused
    # chain must issue <= half the per-kernel chain's dispatches — the
    # acceptance number.  At tp=1 the per-kernel chain has no collective
    # launches to elide (the ratio is ~1.9 there), so the hard floor is
    # slice-gated like overlap_hidden_pct; single-chip draws are
    # trended by obs.history.
    "decode_step_dispatches": {
        "floor": 2.0, "min_devices": 2, "since": 8,
    },
    # fused-mode ms/step: value_max is a gross-regression tripwire (the
    # same bound qwen_decode_step uses); on a real slice the megakernel
    # must at least hold parity with the psum chain it replaces — a
    # fused path SLOWER than per-kernel dispatch means the fusion is
    # broken, not merely unprofitable
    "decode_ms_per_token_fused": {
        "value_max": 20.0, "ratio_spread": (0.90, 3.0),
        "slice_ratio_floor": 0.95, "since": 8,
    },
    # persistent serving megakernel (ISSUE 13; `bench.py decode` /
    # `auto`).  The dispatch count is STATIC (traced step-bundle
    # accounting, ops.persistent_decode.count_bundle_dispatches): the
    # persistent bundle is ONE megakernel launch + the lm_head GEMM per
    # token window — value_max 2.0 IS the acceptance bound, slice-gated
    # because the collective megakernel only builds at tp >= 2 (tp=1
    # runs the pure-XLA reference whose dot chain is the honest count,
    # trended by obs.history; the headless structural pin rides
    # `tdt_lint --persistent`)
    "decode_dispatches_per_bundle": {
        "value_max": 2.0, "min_devices": 2, "since": 13,
    },
    # persistent-bundle ms/token: value_max is the gross-regression
    # tripwire (same bound the fused/step metrics use); on a real slice
    # the device-resident loop must at least hold parity with the psum
    # per-token chain it replaces — a persistent path SLOWER than L
    # host dispatches per token means the chain is broken, not merely
    # unprofitable
    "decode_ms_per_token_persistent": {
        "value_max": 20.0, "slice_ratio_floor": 0.95, "since": 13,
    },
    # measured DMA/MXU overlap of the tile pipeline (tools/overlap.py
    # three-kernel decomposition): a serialized pipeline reads ~0, the
    # r05 capture read 0.76; the clamp makes 1.0 the hard maximum
    "overlap_hidden_pct_m4096": {
        "floor": 0.5, "value_max": 1.0, "since": 5,
    },
    # -- low-precision wire and KV (ISSUE 9; `bench.py wire` / `serve`) --
    # quantized collective payload bytes vs bf16: the packed message is
    # one payload byte per element + the 128-lane scale sidecar, so at
    # h=7168 the ratio is deterministic 1.965x ("quantized moves
    # <= 0.55x the bf16 bytes" = floor 1.82).  On a real slice the value
    # comes from the live comm_wire_bytes counters around a bf16/fp8
    # collective pair (the hard gate binds there — CPU captures are
    # interpret-marked accounting smoke, like the slice-gated
    # decode_step_dispatches discipline); value_max rejects impossible
    # accounting (the ratio cannot exceed 2x + sidecar math)
    "wire_bytes_ratio_bf16_over_quant": {
        "floor": 1.82, "value_max": 2.0, "since": 9,
    },
    # dequant parity as a fraction of the documented codec envelope
    # (`bench.py wire`): ADVISORY — the hard guarantees are the checksum
    # plane and the round-trip property tests; a drift past the envelope
    # is a trend finding.  value_max is the gross tripwire (5x the
    # envelope means the codec, not the chip, regressed)
    "wire_dequant_parity_err_ratio": {
        "warn_max": 1.05, "value_max": 5.0, "since": 9,
    },
    # int8 KV capacity at equal pool bytes: deterministic scheduler
    # replay (SimBackend over the real paged plumbing, pools sized from
    # ONE byte budget via kv_page_bytes — scale sidecars included), so
    # the >= 1.8x concurrency floor is HARD everywhere; 2.0 is the
    # arithmetic ceiling of halved page bytes
    "serve_kv_quant_concurrency": {
        "floor": 1.8, "value_max": 2.05, "since": 9,
    },
    # -- hierarchical multi-slice collectives (ISSUE 10; `bench.py hier`) --
    # per-chip DCN bytes of the hierarchical AllReduce as a fraction of
    # the RS∘AG bound (1/slice_ranks of the payload): value_max 1.02 is
    # the bound + tolerance (bf16 psum sits exactly at 1.0 for n_out=2;
    # the quantized-DCN default ~0.51); the floor rejects impossible
    # under-accounting.  Deterministic byte math from the same source
    # the obs counters and watchdog pricing read
    # (comm.hierarchical.hier_ar_wire_bytes) — CPU captures are
    # interpret-marked (no wire ran), slice captures hard-gate
    "hier_ar_dcn_bytes_ratio": {
        "floor": 0.4, "value_max": 1.02, "since": 10,
    },
    # -- disaggregated prefill/decode serving (ISSUE 12; `bench.py
    # serve_disagg`) -- TTFT + the KV-handoff plane's surface.  On this
    # container the tiers are SimBackends over a MODELED DCN, so every
    # record is interpret-marked (functional smoke, trended by
    # obs.history from round 12 on); the hard claims are slice-gated
    # (min_devices 2) and arm on the first real multislice capture —
    # the same discipline as overlap_hidden_pct / decode_step_dispatches.
    # The p99 bound is a gross tripwire (TTFT under deliberate overload
    # includes queue wait); handoff_ms value_max rejects a handoff that
    # stopped preempting bulk traffic (a page payload is < 1 MB — tens
    # of seconds on the wire means it queued behind a stream);
    # pages/s floor 1 = "the plane shipped SOMETHING"
    "serve_disagg_ttft_ms_p99": {
        "value_max": 30_000.0, "min_devices": 2, "since": 12,
    },
    "handoff_ms_p99": {
        "value_max": 10_000.0, "min_devices": 2, "since": 12,
    },
    "handoff_pages_per_s": {
        "floor": 1.0, "min_devices": 2, "since": 12,
    },
    # burned ladder rungs per replay: a clean wire reads 0; value_max
    # is the gross tripwire (every transfer retrying means the wire or
    # the stamps are broken, not noisy) — trended lower-is-better
    "handoff_retries": {
        "value_max": 64.0, "min_devices": 2, "since": 12,
    },
    # -- request tracing (ISSUE 14; `bench.py serve` / `serve_disagg`) --
    # TDT_TRACE tax: traced vs untraced wall of the SAME seeded replay
    # (the prefix also covers trace_overhead_pct_disagg, the two-tier
    # arm).  warn_max 3.0 is ADVISORY — the acceptance ceiling from the
    # issue, a drift past it is a trend finding (obs.history classifies
    # "overhead" lower-is-better); value_max is the gross tripwire (a
    # trace plane that doubles the serve loop is broken, not taxed).
    # This box's SimBackend replays are interpret-marked (wall jitter on
    # a shared CPU container is not a timing claim); the bounds bind on
    # real-engine captures
    "trace_overhead_pct": {
        "warn_max": 3.0, "value_max": 100.0, "since": 14,
    },
    # -- continuous profiler (ISSUE 16; `bench.py serve` / `serve_disagg`)
    # TDT_PROFILE tax: profiled vs unprofiled wall of the SAME seeded
    # replay (the prefix also covers profile_overhead_pct_disagg, the
    # two-tier arm).  warn_max 2.0 is the issue's acceptance ceiling —
    # an always-on profiler must stay under 2% or it is not always-on;
    # value_max is the gross tripwire.  Interpret-marked on this box's
    # SimBackend replays; the bounds bind on real-engine captures and
    # the trend sentinel ("overhead" -> lower-is-better) guards growth
    # everywhere
    "profile_overhead_pct": {
        "warn_max": 2.0, "value_max": 100.0, "since": 16,
    },
    # -- fleet tier (ISSUE 18; `bench.py fleet`) --
    # p99 TTFT of the diurnal+bursty replay WITH a decode replica lost
    # mid-stream: failover must keep the tail bounded, not merely
    # complete.  The gross 30s ceiling mirrors serve_ttft_ms_p99 —
    # interpret-marked on this box's SimBackend replicas (never
    # hard-gated here); binds on real multi-replica captures
    "fleet_ttft_ms_p99_under_loss": {
        "value_max": 30_000.0, "since": 18,
    },
    # steps from the first sustained decode-dominant demand reading to
    # the membership conversion in the rebalance drill (lower is
    # better; obs.history classifies "steps"/"convergence" accordingly).
    # A drill that never converges reports 1e9 and trips this ceiling
    "fleet_rebalance_convergence_steps": {
        "value_max": 512.0, "since": 18,
    },
    # -- fleet observability (ISSUE 19; `bench.py fleet`) --
    # TDT_FLEET_OBS tax: the SAME seeded N=4 replay bare vs with the
    # per-replica tee federation + decision ledger + fleet-window
    # rotation armed (ledger persistence off).  warn_max 2.0 is the
    # issue's acceptance ceiling — a control plane you cannot afford
    # to leave on is not a control plane; value_max is the gross
    # tripwire.  Interpret-marked on this box's SimBackend replicas;
    # binds on real multi-replica captures, and the trend sentinel
    # ("overhead" -> lower-is-better) guards growth everywhere
    "fleet_obs_overhead_pct": {
        "warn_max": 2.0, "value_max": 100.0, "since": 19,
    },
    # -- regression forensics (ISSUE 20; `bench.py serve`) --
    # Differential-attribution tax on the ARMED profiler: the same
    # seeded replay with the diff computed on EVERY window rotation vs
    # none (production only diffs on a band breach, so this is the
    # worst case).  warn_max 2.0 is the issue's acceptance ceiling —
    # forensics you cannot afford at detection time arrive too late;
    # value_max is the gross tripwire.  Interpret-marked on this box's
    # SimBackend replays; binds on real captures, and the trend
    # sentinel ("overhead" -> lower-is-better) guards growth everywhere
    "diff_overhead_pct": {
        "warn_max": 2.0, "value_max": 100.0, "since": 20,
    },
}

def parse_record(path: str) -> tuple[list[dict], int | None, bool]:
    """(metric lines, envelope rc, truncation detected) from a record:
    either the driver envelope (JSON object whose "tail" holds the
    stdout lines and "rc" the bench exit code) or raw JSON-lines
    (rc None).  Truncation is DETECTABLE when an envelope tail's first
    non-empty line is a partial JSON line (does not start with ``{``) —
    the driver cut mid-line; raw records are never truncated."""
    with open(path) as f:
        text = f.read()
    metrics = []
    rc = None
    truncated = False
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and "tail" in obj:
            text = obj["tail"]
            rc = obj.get("rc")
            nonempty = [ln for ln in text.splitlines() if ln.strip()]
            truncated = bool(nonempty) and \
                not nonempty[0].lstrip().startswith("{")
    except ValueError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            metrics.append(rec)
    return metrics, rc, truncated


_ENVELOPE_GLOB = ("BENCH_r*.json", r"BENCH_r(\d+)\.json$")
_LOCAL_GLOB = ("BENCH_LOCAL_r*.jsonl", r"BENCH_LOCAL_r(\d+)\.jsonl$")


def _newest(root: str, spec: tuple[str, str]) -> tuple[str | None, int]:
    glob_pat, regex = spec
    paths = glob.glob(os.path.join(root, glob_pat))

    def round_no(p):
        m = re.search(regex, p)
        return int(m.group(1)) if m else -1

    if not paths:
        return None, -1
    best = max(paths, key=round_no)
    return best, round_no(best)


def newest_record(root: str) -> str | None:
    """Newest driver-envelope record (``BENCH_rNN.json``)."""
    return _newest(root, _ENVELOPE_GLOB)[0]


def newest_local_record(root: str) -> str | None:
    """Newest on-disk bench-written record (``BENCH_LOCAL_rNN.jsonl``):
    the complete JSONL stream ``bench.py auto`` tees to disk, immune to
    the driver envelope's tail truncation (VERDICT r5 next #1)."""
    return _newest(root, _LOCAL_GLOB)[0]


# Round 6 is when bench.py started persisting the local record: from
# there on, an envelope-only record with DETECTABLE truncation is a
# hard failure (the full stream exists on the bench host — commit it),
# not a warning.  Older committed envelopes (r05's truncated head) keep
# the legacy warning path: no local record ever existed for them.
LOCAL_RECORD_SINCE = 6


def _check_metric(rec: dict, claim: dict) -> tuple[list[str], list[str]]:
    """(hard failures, warnings) for one recorded metric line."""
    fails, warns = [], []
    name = rec["metric"]
    if rec.get("interpret"):
        # the bench marked this capture as CPU-interpret (functional
        # smoke, not timing — e.g. overlap_collective's small-shape
        # structure run): simulated numbers must never trip hard claims,
        # but the record ran, so completeness is satisfied upstream
        warns.append(
            f"{name}: interpret-mode capture (functional smoke, not "
            f"timing) — hard claims not applied to simulated numbers"
        )
        return fails, warns
    value = rec.get("value")
    vb = rec.get("vs_baseline")
    bv = rec.get("baseline_value")
    unit = rec.get("unit", "")

    floor = claim.get("floor")
    if floor is not None and value is not None and value < floor:
        # the gate, not the bench, owns the retry decision (ADVICE r5
        # low #3): bench.py always publishes the FIRST draw and attaches
        # the symmetric retry; a dip whose retry clears the floor is a
        # transient throttle (warning), a double miss is a regression
        retry = rec.get("retry_value")
        if retry is not None and retry >= floor:
            warns.append(
                f"{name}: first draw value={value} {unit} dipped below "
                f"the floor {floor} but the retry read {retry} — "
                f"transient chip throttle, not a regression"
            )
        else:
            fails.append(
                f"{name}: value={value} {unit} below the claimed floor "
                f"{floor} — kernel or measurement-protocol regression"
            )
    ceil = claim.get("value_ceiling")
    if ceil is not None and value is not None and value > ceil:
        fails.append(
            f"{name}: value={value} {unit} exceeds the physical ceiling "
            f"{ceil} — suspect capture (estimator or accounting bug)"
        )
    vmax = claim.get("value_max")
    if vmax is not None and value is not None and value > vmax:
        fails.append(
            f"{name}: value={value} {unit} above the allowed maximum {vmax}"
        )
    wmax = claim.get("warn_max")
    if wmax is not None and value is not None and value > wmax:
        warns.append(
            f"{name}: value={value} {unit} above the advisory maximum "
            f"{wmax} — drifting tax; investigate before it regresses a "
            f"real floor"
        )
    bceil = claim.get("baseline_ceiling")
    if bceil is not None and bv is not None and bv > bceil:
        fails.append(
            f"{name}: baseline_value={bv} {unit} exceeds the physical "
            f"ceiling {bceil} — the baseline measurement is impossible; "
            f"the capture (not the claim) is wrong"
        )
    exact = claim.get("exact_ratio")
    if exact is not None and vb is not None:
        lo, hi, band = exact
        if not (lo * (1 - band) <= vb <= hi * (1 + band)):
            fails.append(
                f"{name}: deterministic vs_baseline={vb} outside "
                f"[{lo}, {hi}] — payload/accounting regression"
            )
    srf = claim.get("slice_ratio_floor")
    if srf is not None and vb is not None \
            and int(rec.get("devices", 1) or 1) > 1 and vb < srf:
        fails.append(
            f"{name}: vs_baseline={vb} below the slice ratio floor {srf} "
            f"on a {rec.get('devices')}-device capture — the distributed "
            f"mode lost to its baseline"
        )
    spread = claim.get("ratio_spread")
    if spread is not None and vb is not None:
        lo, hi = spread
        if not (lo <= vb <= hi):
            warns.append(
                f"{name}: vs_baseline={vb} outside the documented observed "
                f"spread [{lo}, {hi}] (informational — the baseline swings "
                f"with chip state; the binding claim is the absolute floor)"
            )
    return fails, warns


def check(root: str) -> int:
    env_path, env_round = _newest(root, _ENVELOPE_GLOB)
    local_path, local_round = _newest(root, _LOCAL_GLOB)
    if env_path is None and local_path is None:
        print("no BENCH_r*.json / BENCH_LOCAL_r*.jsonl found — "
              "nothing to check")
        return 0
    # the on-disk local record is the complete stream by construction:
    # prefer it whenever it is at least as new as the driver envelope
    using_local = local_path is not None and local_round >= env_round
    if using_local:
        path, record_round = local_path, local_round
    else:
        path, record_round = env_path, env_round
    metrics, rc, truncated = parse_record(path)
    if using_local:
        # preferring the local stream must not drop the crash gates the
        # envelope used to carry: (a) the same-round envelope's rc still
        # binds; (b) bench.py only writes a local record in `auto` mode,
        # whose stream always ENDS with the sweep sentinel — a local
        # record without one is a sweep that died mid-run, not a
        # targeted capture exempt from completeness
        if env_round == local_round and env_path is not None:
            rc = parse_record(env_path)[1]
        if not any(r["metric"] == "bench_sweep_complete" for r in metrics):
            print(f"{os.path.basename(path)}: local record has no "
                  f"bench_sweep_complete sentinel — the `auto` sweep died "
                  f"before finishing; the record is incomplete")
            return 1
    if truncated and record_round >= LOCAL_RECORD_SINCE:
        # the envelope is a FALLBACK from round 6 on: detectable
        # truncation without the local record means values were lost
        # that bench.py provably wrote to disk — fail loudly instead of
        # gating a partial stream
        print(f"{os.path.basename(path)}: envelope tail is truncated "
              f"(first line is a partial record) and no "
              f"BENCH_LOCAL_r{record_round:02d}.jsonl is committed — "
              f"commit the complete on-disk record bench.py wrote "
              f"(or raise the driver tail budget)")
        return 1
    if not metrics:
        print(f"{path}: no metric lines parsed — record format drifted?")
        return 1
    failures, warnings = [], []
    checked = 0
    seen_prefixes = set()
    for rec in metrics:
        hit = next(
            ((prefix, c) for prefix, c in CLAIMS.items()
             if rec["metric"].startswith(prefix)),
            None,
        )
        if hit is None or record_round < hit[1].get("since", 0):
            continue
        if int(rec.get("devices", 1) or 1) < hit[1].get("min_devices", 1):
            # slice-gated claim on a single-chip capture: nothing to gate
            continue
        seen_prefixes.add(hit[0])
        checked += 1
        f, w = _check_metric(rec, hit[1])
        failures.extend(f)
        warnings.extend(w)
    # every BINDING claim must have a matching metric in the record: a
    # renamed bench metric or a crashed bench mode would otherwise make
    # its claims silently unchecked — the gate must notice absence, not
    # just violation.  Completeness binds to FULL-SWEEP records,
    # identified explicitly: `bench.py auto` always ends with the
    # bench_sweep_complete sentinel (value 0 = some mode crashed).
    # Driver-envelope records with a nonzero rc fail outright —
    # a sweep that died before the sentinel must not pass by absence.
    #
    # Driver envelopes keep only the last N bytes of stdout, so a healthy
    # sweep's HEAD lines can be tail-truncated away (ADVICE r5 medium #1,
    # the BENCH_r05 false "bench mode crashed").  The sentinel therefore
    # carries ``emitted``, the list of metric names the sweep actually
    # printed: a claim whose line was truncated but whose name is in
    # ``emitted`` is a WARNING (its value went ungated this round), not a
    # crash; truly absent names still fail hard.
    sentinel = next(
        (r for r in metrics if r["metric"] == "bench_sweep_complete"), None
    )
    if rc not in (None, 0):
        failures.append(
            f"driver envelope records bench exit code {rc} — the sweep "
            f"crashed; the record is incomplete"
        )
    if sentinel is not None:
        if not sentinel.get("value"):
            failures.append(
                "bench_sweep_complete=0 — one or more bench modes crashed "
                "mid-sweep (see the driver log)"
            )
        emitted = sentinel.get("emitted")
        # legacy full-sweep envelopes (captured before the sentinel grew
        # ``emitted``) are tail-truncated BY CONSTRUCTION, and their
        # sentinel=1 already attests no mode crashed: absence there is
        # truncation, not a crash.  Only envelopes (rc recorded) qualify —
        # a raw JSONL record was never truncated, so absence stays hard.
        legacy_truncated = (emitted is None and rc is not None
                            and bool(sentinel.get("value")))
        sweep_devices = int(sentinel.get("devices", 1) or 1)
        for prefix, claim in CLAIMS.items():
            if (record_round < claim.get("since", 0)
                    or prefix in seen_prefixes):
                continue
            if claim.get("min_devices", 1) > sweep_devices:
                # slice-only metric; this sweep ran on fewer devices, so
                # absence is expected, not a crashed bench mode
                continue
            if emitted is not None and any(
                    str(name).startswith(prefix) for name in emitted):
                warnings.append(
                    f"claimed metric {prefix!r} was emitted by the sweep "
                    f"but tail-truncated from the envelope — its value is "
                    f"unchecked this round (raise the driver tail budget)"
                )
            elif legacy_truncated:
                warnings.append(
                    f"claimed metric {prefix!r} absent from the truncated "
                    f"envelope tail (legacy sentinel without 'emitted'; "
                    f"sentinel=1 attests the mode ran) — value unchecked"
                )
            else:
                failures.append(
                    f"claimed metric {prefix!r} is MISSING from the record "
                    f"— its bench mode crashed or the metric was renamed"
                )
    tag = os.path.basename(path)
    for w in warnings:
        print(f"{tag}: WARNING {w}")
    if failures:
        print(f"{tag}: {len(failures)} primary claim(s) violated:")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"{tag}: {checked} claimed metrics satisfy their primary claims"
          f" ({len(warnings)} spread warnings)")
    return 0


def print_trend(root: str) -> None:
    """The ``--trend`` hook: round-over-round trajectory warnings from
    ``obs.history`` printed next to the floor verdicts.  Informational —
    never changes the gate's exit code (run ``scripts/bench_history.py
    --check`` for the loud consistency half)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from triton_distributed_tpu.obs import history
    except Exception as e:  # the gate must not die on the trend add-on
        print(f"trend: unavailable ({type(e).__name__}: {e})")
        return
    rounds = history.load_rounds(root)
    warnings = history.all_warnings(history.analyze(rounds))
    for w in warnings:
        print(f"trend: WARNING {w}")
    if not warnings:
        print(f"trend: {len(rounds)} committed round(s), no trajectory "
              f"warnings")


if __name__ == "__main__":
    argv = sys.argv[1:]
    trend = "--trend" in argv
    argv = [a for a in argv if a != "--trend"]
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    rc = check(root)
    if trend:
        print_trend(root)
    sys.exit(rc)
