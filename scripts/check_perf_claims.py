#!/usr/bin/env python
"""Fail when documented perf claims drift from the newest driver record.

The round-3 review found `docs/perf.md` and op docstrings quoting ratios
(grouped matmul "1.05-1.09x", decode "1.27x") that the driver's
`BENCH_r03.json` capture contradicted (0.84x / 0.97x).  This script
closes that loop permanently: the headline claims live HERE as a
machine-readable registry (docs/perf.md's table quotes the same ranges
and points at this file), and every run checks the newest `BENCH_r*.json`
at the repo root against them.

A claim is a range ``[lo, hi]`` of `vs_baseline` values the docs assert.
The captured value must land inside ``[lo * (1 - BAND), hi * (1 + BAND)]``
where BAND is the documented noise band of the interleaved-median
protocol: identical-program A/A runs on the tunneled chip put the
captured ratio spread at up to ~8% (bench.py's methodology note), so a
capture within that band of the claimed range is consistent, and
anything outside it means the docs or the code regressed — the run
fails and says which.

Usage: python scripts/check_perf_claims.py [repo_root]
Exit 0 = every recorded metric with a claim is consistent.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

# Documented noise band of the capture protocol (A/A identical-program
# interleaved medians spread up to ~8% between invocations).
BAND = 0.08

# metric-name prefix -> (claimed lo, claimed hi, since_round[, band]) of
# vs_baseline.  These ARE the ranges docs/perf.md quotes; edit both
# together.  ``since_round`` scopes a claim to records captured at or
# after the round whose code makes it true (BENCH_r03 predates the
# round-4 backend-dispatch + pad-elision work, so the round-4 claims
# must not retroactively fail against it).  ``band`` overrides BAND for
# deterministic claims (a byte ratio has no measurement noise — any
# drift is a payload-format regression and must fail exactly).
# The ranges are the FULL spread of repeated same-code captures across
# the tunneled chip's clock states (docs/perf.md's chip-state note):
# our Pallas kernels hold stable absolute throughput while XLA's
# baselines swing 2-3x with chip state, so the RATIO of a single run is
# a draw from these ranges — the wide 4096^3 upper bound is XLA's
# documented 53-190 TF/s instability at that shape, and the sub-1.0
# lower tails are states where XLA's paths run unusually fast.
CLAIMS = {
    "single_chip_gemm_7168_bf16": (0.95, 1.15, 4),
    "single_chip_gemm_m4096_n4096_k4096_bf16": (0.95, 4.0, 4),
    "single_chip_gemm_m8192_n2048_k7168_bf16": (0.90, 1.6, 4),
    # ours and the unfused baseline degrade DIFFERENTLY with chip state
    # (the S x S-materializing baseline is HBM-bound): measured spread
    # across states this round was 5.5-12.3x
    "flash_attn_b1_h32_s4096_d128": (5.0, 13.0, 3),
    "decode_attn_b8_h32_hk8_s8192_d128": (0.70, 1.35, 3),
    "group_gemm_t8192_k7168_n2048_e8": (0.90, 1.30, 4),
    "tp_mlp_m4096_k7168_i7168_tp1": (0.95, 1.30, 3),
    "qwen_decode_step_b128_tp1_psum_vs_ar": (0.95, 1.35, 3),
    "moe_ep_a2a_fp8_wire_bytes_h7168": (1.96, 1.97, 3, 0.0),  # exact ratio
}


def parse_record(path: str) -> list[dict]:
    """Metric lines from a BENCH_r*.json: either the driver envelope
    (JSON object whose "tail" holds the stdout lines) or raw JSON-lines."""
    with open(path) as f:
        text = f.read()
    metrics = []
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and "tail" in obj:
            text = obj["tail"]
    except ValueError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            metrics.append(rec)
    return metrics


def newest_record(root: str) -> str | None:
    paths = glob.glob(os.path.join(root, "BENCH_r*.json"))

    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return max(paths, key=round_no) if paths else None


def check(root: str) -> int:
    path = newest_record(root)
    if path is None:
        print("no BENCH_r*.json found — nothing to check")
        return 0
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    record_round = int(m.group(1)) if m else 0
    metrics = parse_record(path)
    if not metrics:
        print(f"{path}: no metric lines parsed — record format drifted?")
        return 1
    failures = []
    checked = 0
    for rec in metrics:
        name, vb = rec["metric"], rec.get("vs_baseline")
        claim = next(
            (c for prefix, c in CLAIMS.items() if name.startswith(prefix)),
            None,
        )
        if claim is None or vb is None:
            continue
        lo, hi, since, *rest = claim
        band = rest[0] if rest else BAND
        if record_round < since:
            continue
        checked += 1
        if not (lo * (1 - band) <= vb <= hi * (1 + band)):
            failures.append(
                f"  {name}: captured vs_baseline={vb} outside claimed "
                f"[{lo}, {hi}] (±{band:.0%} noise band) — update "
                f"docs/perf.md + scripts/check_perf_claims.py or fix the "
                f"regression"
            )
    tag = os.path.basename(path)
    if failures:
        print(f"{tag}: {len(failures)} claim(s) drifted from the record:")
        print("\n".join(failures))
        return 1
    print(f"{tag}: {checked} claimed metrics consistent with the record")
    return 0


if __name__ == "__main__":
    sys.exit(check(sys.argv[1] if len(sys.argv) > 1 else
                   os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
