#!/usr/bin/env python
"""Static protocol lint for the distributed Pallas kernels.

Runs the ``tdt.analysis`` verifier — signal balance, deadlock freedom,
write-overlap, collective divergence (docs/static_analysis.md) — over
every registered kernel builder in ``comm/`` and ``ops/`` (push/ring/bidir
AllGather, ring ReduceScatter, one/two-shot AllReduce, EP all-to-all
dispatch/combine, AG-GEMM uni/bidir, GEMM-RS, GEMM-AR) across rank counts
{2, 4, 8}.  Pure CPU: no hardware, no interpret mode, no jax arrays beyond
eager ring-index arithmetic — this is the protocol gate a CI box can run.

Usage:
    python scripts/tdt_lint.py                   # full matrix
    python scripts/tdt_lint.py --ranks 2,4       # restrict rank counts
    python scripts/tdt_lint.py --kernel gemm_rs  # name filter (substring)
    python scripts/tdt_lint.py --selftest        # seeded-bad fixture battery
    python scripts/tdt_lint.py --dpor            # schedule-exhaustive (DPOR) gate
    python scripts/tdt_lint.py --completeness    # cross-subsystem wiring gate
    python scripts/tdt_lint.py --faults          # fault-injection matrix
    python scripts/tdt_lint.py --faults --seed 7 # reseed the injection
    python scripts/tdt_lint.py --timeline        # flight-timeline smoke
    python scripts/tdt_lint.py --history         # bench-record trend gate
    python scripts/tdt_lint.py --serve           # scheduler overload smoke
    python scripts/tdt_lint.py --integrity       # data-integrity gate
    python scripts/tdt_lint.py --hier            # hierarchical (ICIxDCN) gate
    python scripts/tdt_lint.py --trace           # request-tracing gate
    python scripts/tdt_lint.py --profile         # continuous-profiler gate
    python scripts/tdt_lint.py --pages           # page-lifetime ownership gate
    python scripts/tdt_lint.py --fleet           # fleet-tier (N-replica) gate
    python scripts/tdt_lint.py --fleetobs        # fleet-observability gate
    python scripts/tdt_lint.py --regress         # regression-forensics gate
    python scripts/tdt_lint.py --all             # every gate, one exit code
    python scripts/tdt_lint.py --json report.json

``--faults`` runs the ``tdt.resilience`` fault-injection matrix
headlessly (docs/robustness.md): every fault class (dropped/delayed
notify, stale credit, straggler, rank abort) against every guarded
kernel family — the decode megakernel's semaphore-chained
``fused_mlp_ar`` included — asserting each injection is either
DETECTED (timeout / hazard naming the pending semaphore or chunk) or
SURVIVED (completed in budget with balanced credits).

``--timeline`` is the flight-recorder regression smoke
(docs/observability.md "Flight recorder"): record a 2-rank AllGather
under deterministic record mode, reconstruct the cross-rank timeline
(``obs.timeline``), and assert the reconstruction completes with
BALANCED attribution — symmetric per-rank exposed-wait totals and every
recv stall named with its (semaphore, chunk, peer) triple.  Headless
and CPU-only, like the rest of the lint.

``--serve`` is the continuous-batching scheduler's overload smoke
(docs/serving.md): a seeded 64-request open-loop trace overcommitting
the KV-page budget runs through the REAL scheduler (deterministic
SimBackend over the real paged-cache plumbing) WITH fault injection on
(a rank abort mid-decode), asserting zero leaked pages, a monotone
queue drain after arrivals stop, every request terminal, and
per-request isolation; then the fault matrix's scheduler cells
(``resilience.run_scheduler_matrix``) must each be detected-or-
survived.  Headless and CPU-only.

``--integrity`` is the data-integrity gate (docs/robustness.md "Data
integrity"): both corruption fault classes (``corrupt_payload`` — bytes
flipped in flight; ``corrupt_kv_page`` — bytes flipped at rest) against
every guarded kernel family through the record-mode checksum protocol,
the scheduler KV-page-poison cell (audit detection + preemption-
recompute recovery), and the live-verifier selftest battery (every
``verify_*`` helper must catch a planted flip and pass the clean
input; quarantine must open at its threshold).  Exit 1 on any
undetected-unsurvived cell.  Headless and CPU-only.

``--hier`` is the hierarchical multi-slice gate (ISSUE 10,
docs/perf.md "Hierarchical collectives"): the two-level (ICI x DCN)
protocol matrix at the {2x2, 2x4, 4x2} slice layouts plus the
scheduled-emission A2A variant at ranks {2,4,8} through the static
verifier; the fault-injection cells over every hierarchical kernel
(the dropped-inter-slice-credit class included — drop_notify /
stale_credit landing on the dcn semaphores must be DETECTED); and the
schedule-order selftest on a synthetic 2x4 topology (every DCN-bound
chunk group must precede every ICI-bound one, farthest-first within
each class, self last — and the ordering must FLIP when the synthetic
calibration says the ICI is the slower wire).  Headless and CPU-only.

``--handoff`` is the disaggregated-serving gate (ISSUE 12,
docs/serving.md "Disaggregated serving"): a seeded two-tier replay
(prefill tier -> ModeledDCN -> decode tier through the REAL router)
with a transfer drop, a corrupt page in flight, and a prefill-slice
abort injected — zero leaked pages on BOTH tiers, every faulted
request completes via the re-prefill fallback (or a clean retry) with
token parity vs the deterministic golden, monotone drain; then the
handoff fault cells (``resilience.run_handoff_matrix``: the five
threat-model classes incl. decode-tier saturation -> colocated shed)
must each be detected-or-survived.  Headless and CPU-only.

``--persistent`` is the persistent-decode gate (ISSUE 13,
docs/perf.md "Persistent decode loop"): the chained multi-layer
protocol (2L ring reductions on ONE re-armed semaphore set) through
the static verifier at ranks {2,4,8}; every fault class against the
chain with the must-detect classes naming a semaphore of the shared
set (the inter-layer dependency edge); a HEADLESS dispatch-count
assertion — the step-bundle harness (``lax.scan`` + lm_head) adds
exactly ONE launch-shaped equation around the megakernel, and the
module carries exactly ONE ``pallas_call``, so a persistent step
bundle is <= 2 dispatches (``decode_dispatches_per_bundle``'s claim);
and a scheduler window-parity smoke — ``steps_per_dispatch`` 4 vs 1
over a seeded pool-pressured trace must complete the SAME requests
with IDENTICAL tokens (membership changes between windows, preemption
re-queued cleanly), zero leaked pages, in fewer dispatches.  Headless
and CPU-only.

``--trace`` is the request-tracing gate (ISSUE 14,
docs/observability.md "Request tracing"): a seeded two-tier replay
(the ``--handoff`` harness shape) with a transfer DROP injected runs
with ``TDT_TRACE`` armed, asserting every terminal request carries a
GAPLESS span chain (no hop unaccounted), the SLO attributor's phase
budgets sum exactly to each trace's end-to-end latency, the TTFT /
request-latency p99 exemplar ids resolve to retained ring traces, and
the drop-faulted request's trace names every retry rung plus the
re-prefill fallback.  Headless and CPU-only.

``--profile`` is the continuous-profiler gate (ISSUE 16,
docs/observability.md "Continuous profiling"): an ARMED
(``TDT_PROFILE``) seeded two-tier replay must rotate windows through
the real scheduler/router step hooks; every registry family with an
``obs.costs`` calculator (the set cross-checked against the
completeness wiring table) must land a live per-family rollup whose
exposed/compute/critical/SOL/skew attribution agrees with the offline
``obs.timeline`` reconstructor on the SAME capture; and the anomaly
selftest must pass in BOTH directions — the clean replay stays quiet,
the seeded wire-inflation regression is caught with the (semaphore,
chunk, peer) stall triple and the p99 exemplar named.  Headless and
CPU-only.

``--dpor`` is the schedule-exhaustive gate (ISSUE 15,
docs/static_analysis.md "Schedule exhaustiveness"): the canonical
maximal execution is sound for deadlock but NOT for the credit->wait
matching on multi-producer semaphore pools, so this leg model-checks
every registry case over ALL schedules up to equivalence (dynamic
partial-order reduction: sleep sets + singleton persistent sets over
the credit-FIFO independence relation; branch points exactly at
multi-producer credit races), re-running deadlock + write-overlap per
class, with a context-switch-bounded mode (``--explore-bound``,
default 2) and per-case schedule/time caps that print PRUNED rather
than masking; then the DPOR fixture selftest pins BOTH directions —
each order-dependent fixture passes every canonical check AND is
flagged under reordering with the reused slot named.

``--completeness`` is the cross-subsystem wiring gate (ISSUE 15):
every family in ``analysis.registry`` must have an
``obs.costs.FAMILY_COSTS`` calculator, a ``resilience.fallbacks``
entry (or documented watchdog-only / rides-base-family status),
fault-matrix coverage, and a registered unique ``collective_id`` —
golden-pinned in ``analysis.completeness`` so a family added without
full wiring fails with the diff as the message; plus the static
VMEM-footprint check on every family's DEFAULT tile config
(``analysis.footprint``) at its representative serving shape.

``--pages`` is the page-lifetime ownership gate (docs/static_analysis.md
"Page lifetime checking"): the DPOR explorer over the clean two-tier
handoff/preempt/colocate/shared-release page scenarios (every schedule
class leak-free, no use-after-free / read-before-stamp / double-free /
scrub-under-reader), the seeded-bad lifecycle fixture battery in both
directions, and a static ownership re-check of every fault-matrix
serving cell's recorded page trace.

``--fleet`` is the fleet-tier gate (ISSUE 18, docs/serving.md "Fleet
tier"): a seeded N=4 replay (two prefill + two decode replicas through
the REAL ``serve.FleetRouter``) with one replica LOST mid-decode and a
second replica FLAPPING through its sticky ``replica:<id>`` breaker —
every faulted request must complete on a survivor with token parity vs
the deterministic golden, EXACTLY the flapping replica must walk
quarantine (drain-before-evict), the lost replica must be named in
``lost_replicas``, and zero pages may leak on ANY replica (per-pool
lifecycle discharge); then the fleet fault cells
(``resilience.run_fleet_matrix``: replica-abort failover, flap
quarantine, rebalance-under-load membership conversion, quarantine
readmission) must each be detected-or-survived.  Headless and
CPU-only.

``--fleetobs`` is the fleet-observability gate (ISSUE 19,
docs/observability.md "Fleet observability"): the ``--fleet`` replay
shape (N=4, one replica lost mid-decode, one flapping into
quarantine) re-runs with ``TDT_FLEET_OBS`` armed — every FleetRouter
actuation the replay exercised must land in the decision ledger with
counts reconciling against the router's own counters, the
quarantine-drain decision must name an exemplar trace id that
resolves in the retained ring, the ledger ring must round-trip
through its rotated JSONL segments
(``obs.history.load_decision_records``), the fleet-merged latency
sketches must reconcile EXACTLY with the union stream (the tee
federation is lossless, not approximate), the decision-coverage
golden must discharge statically in both directions
(``analysis.completeness.check_decision_coverage``), and the
fleet-anomaly selftest must pass both directions (clean replay
quiet, seeded single-replica inflation breaches the p99 band AND the
same-role skew gauge with the exemplar + window decisions carried).
Headless and CPU-only.

``--regress`` is the regression-forensics gate (ISSUE 20,
docs/observability.md "Regression forensics"): the ``obs.diff``
selftest, both directions — a healthy window diffed against a
wire-inflated replay of itself must attribute the delta to the
injected (family, phase) with the dominant stall triple and an
exemplar trace id that resolves in the retained ring, under the
exactness contract (per-term deltas + residual sum to the total
metric delta EXACTLY); an identical-capture diff must rank nothing;
and the fast-vs-slow trace pairing must rank the inflated phase
first.  Plus the direction-coverage golden
(``analysis.completeness.check_direction_coverage``): every bench
metric classifies under a named ``obs.history.DIRECTION_RULES`` row,
no dead rules, no dead allowlist entries.  Headless and CPU-only.

``--all`` runs every gate above — verify matrix, ``--dpor``,
``--completeness``, ``--faults``, ``--timeline``, ``--serve``,
``--history``, ``--integrity``, ``--quant``, ``--hier``,
``--handoff``, ``--persistent``, ``--trace``, ``--profile``,
``--pages``, ``--fleet``, ``--fleetobs``, ``--regress`` — and
summarizes them under a single exit code (the CI entry; see README).

``--history`` runs the bench-record trend sentinel
(``scripts/bench_history.py --check``): exit 1 when a committed
``BENCH_rNN`` round is internally inconsistent (local/envelope value
disagreement, sentinel-listed metric missing from a complete local
stream, crashed sweep); round-over-round decline / below-band findings
print as warnings (docs/observability.md "Live telemetry").

Exit status: 0 = every kernel clean (or selftest/fault matrix passed);
1 = violations (each printed with the violating semaphore/chunk named).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the lint needs only eager scalar arithmetic; never try to grab a TPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ranks", default="2,4,8",
                    help="comma-separated rank counts (default 2,4,8)")
    ap.add_argument("--kernel", default=None,
                    help="only verify cases whose name contains this")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the seeded-bad fixtures are each flagged "
                         "and a clean kernel passes")
    ap.add_argument("--faults", action="store_true",
                    help="run the resilience fault-injection matrix: every "
                         "fault class must be detected or survived")
    ap.add_argument("--timeline", action="store_true",
                    help="flight-timeline smoke: record a 2-rank AG, "
                         "reconstruct, assert balanced attribution")
    ap.add_argument("--history", action="store_true",
                    help="bench-record trend gate: committed rounds must "
                         "be internally consistent; trends warn")
    ap.add_argument("--serve", action="store_true",
                    help="scheduler overload smoke: seeded 64-request "
                         "trace with fault injection, zero leaked pages, "
                         "monotone drain; plus the scheduler fault cells")
    ap.add_argument("--integrity", action="store_true",
                    help="data-integrity gate: corruption fault classes "
                         "over every kernel family, the scheduler "
                         "KV-poison cell, and the verifier selftest")
    ap.add_argument("--quant", action="store_true",
                    help="low-precision wire gate (ISSUE 9): codec "
                         "round-trip selftest battery (error envelopes, "
                         "edge rows, poisoned-scale-sidecar cell), the "
                         "quantized-variant protocol matrix at ranks "
                         "{2,4,8}, and the corruption fault cells over "
                         "the quantized kernels")
    ap.add_argument("--hier", action="store_true",
                    help="hierarchical (ICI x DCN) gate (ISSUE 10): "
                         "two-level protocol matrix at slice layouts "
                         "{2x2,2x4,4x2}, fault cells incl. the dropped "
                         "inter-slice credit, and the schedule-order "
                         "selftest on a synthetic 2x4 topology")
    ap.add_argument("--persistent", action="store_true",
                    help="persistent-decode gate (ISSUE 13): chained "
                         "multi-layer protocol matrix + fault cells with "
                         "the inter-layer semaphore named + the headless "
                         "dispatch-count assertion + a scheduler "
                         "window-parity smoke")
    ap.add_argument("--dpor", action="store_true",
                    help="schedule-exhaustive gate (ISSUE 15): the DPOR "
                         "explorer over every registry case (bounded "
                         "mode; see --explore-bound), plus the "
                         "canonical-pass/DPOR-fail fixture selftest in "
                         "both directions")
    ap.add_argument("--explore-bound", default="2",
                    help="DPOR preemption bound for --dpor (integer, or "
                         "'exact' for the unbounded mode; default 2)")
    ap.add_argument("--completeness", action="store_true",
                    help="cross-subsystem completeness gate (ISSUE 15): "
                         "every registry family must have a cost "
                         "calculator, a fallback or documented "
                         "watchdog-only status, fault-matrix coverage "
                         "and a unique collective_id (golden-pinned), "
                         "and every DEFAULT tile config must fit the "
                         "static VMEM footprint budget")
    ap.add_argument("--trace", action="store_true", dest="trace_gate",
                    help="request-tracing gate (ISSUE 14): seeded "
                         "two-tier replay with a transfer drop under "
                         "TDT_TRACE — gapless span chains, attributor "
                         "sums equal e2e latency, exemplar ids resolve, "
                         "the faulted trace names its retry/re-prefill "
                         "rungs")
    ap.add_argument("--handoff", action="store_true",
                    help="disaggregated-serving gate (ISSUE 12): seeded "
                         "two-tier replay with a transfer drop, a corrupt "
                         "page and a prefill-slice abort injected (zero "
                         "leaked pages on both tiers, faulted requests "
                         "complete via re-prefill), plus the handoff "
                         "fault cells")
    ap.add_argument("--profile", action="store_true",
                    help="continuous-profiler gate (ISSUE 16): armed "
                         "two-tier replay rotates windows through the "
                         "step hooks, every cost-calculated family "
                         "lands a live rollup agreeing with the "
                         "offline timeline on the same capture, and "
                         "the anomaly selftest passes both directions")
    ap.add_argument("--pages", action="store_true", dest="pages_gate",
                    help="page-lifetime ownership gate (ISSUE 17): the "
                         "DPOR sweep over the clean two-tier page "
                         "scenarios, the seeded-bad lifecycle fixture "
                         "selftest both directions, and a static "
                         "ownership re-check of every fault-matrix "
                         "serving cell's recorded page trace")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet-tier gate (ISSUE 18): seeded N=4 "
                         "replay with one replica lost mid-decode and "
                         "one flapping into quarantine (every faulted "
                         "request completes on a survivor with token "
                         "parity, exactly the flapping replica "
                         "quarantine-evicted, zero leaked pages per "
                         "replica), plus the fleet fault cells")
    ap.add_argument("--fleetobs", action="store_true",
                    help="fleet-observability gate (ISSUE 19): the "
                         "armed (TDT_FLEET_OBS) N=4 replay — every "
                         "actuation ledgered with counts reconciling "
                         "against the router counters, the quarantine "
                         "decision naming a resolvable exemplar trace, "
                         "the JSONL segments round-tripping, the "
                         "fleet-merged sketches exactly equal to the "
                         "union stream, the decision-coverage golden "
                         "discharged both directions, and the "
                         "fleet-anomaly selftest both directions")
    ap.add_argument("--regress", action="store_true",
                    help="regression-forensics gate (ISSUE 20): the "
                         "obs.diff selftest both directions (seeded "
                         "wire inflation attributed to the injected "
                         "family/phase/stall with a resolving "
                         "exemplar under the exactness contract; "
                         "identical captures rank nothing) plus the "
                         "direction-coverage golden")
    ap.add_argument("--all", action="store_true", dest="all_gates",
                    help="run every gate (verify matrix, --faults, "
                         "--timeline, --serve, --history, --integrity, "
                         "--quant, --hier, --handoff, --persistent, "
                         "--trace, --profile, --pages, --fleet, "
                         "--fleetobs, --regress) with one summarized "
                         "exit code")
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-injection target sampling seed (--faults)")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the per-case results as JSON")
    args = ap.parse_args(argv)

    if args.all_gates:
        return _run_all(args)
    if args.dpor:
        return _run_dpor(args)
    if args.completeness:
        return _run_completeness(args)
    if args.faults:
        return _run_faults(args)
    if args.timeline:
        return _run_timeline(args)
    if args.history:
        return _run_history(args)
    if args.serve:
        return _run_serve(args)
    if args.integrity:
        return _run_integrity(args)
    if args.quant:
        return _run_quant(args)
    if args.hier:
        return _run_hier(args)
    if args.handoff:
        return _run_handoff(args)
    if args.persistent:
        return _run_persistent(args)
    if args.trace_gate:
        return _run_trace(args)
    if args.profile:
        return _run_profile(args)
    if args.pages_gate:
        return _run_pages(args)
    if args.fleet:
        return _run_fleet(args)
    if args.fleetobs:
        return _run_fleetobs(args)
    if args.regress:
        return _run_regress(args)

    from triton_distributed_tpu import analysis

    if args.selftest:
        from triton_distributed_tpu.analysis import fixtures

        problems = fixtures.run_selftest()
        # the battery also proves a shipped kernel still verifies clean
        clean = analysis.verify_all(ranks=(4,), kernel_filter="allgather")
        problems += [
            f"{case.name}: expected clean, got {[str(v) for v in vs]}"
            for case, vs in clean if vs
        ]
        for p in problems:
            print(f"SELFTEST FAIL: {p}")
        if problems:
            return 1
        print("selftest OK: every seeded-bad fixture flagged with the "
              "violating semaphore/chunk named; shipped kernels clean")
        return 0

    return _run_verify(args)


def _run_dpor(args) -> int:
    """The schedule-exhaustive gate (ISSUE 15, docs/static_analysis.md
    "Schedule exhaustiveness"): (1) the DPOR explorer over every
    registry kernel case — all schedules up to equivalence, deadlock +
    write-overlap re-checked per class, branch points exactly at
    multi-producer credit races; (2) the DPOR fixture selftest, pinning
    BOTH directions of the soundness gap (each fixture passes every
    canonical check AND fails under reordering with the reused slot
    named).  A case hitting a resource cap prints PRUNED — bounded-mode
    verification, never silent."""
    from triton_distributed_tpu import analysis
    from triton_distributed_tpu.analysis import fixtures

    # same convention as TDT_VERIFY_EXPLORE: 'exact' or any negative
    # integer means unbounded (a raw negative bound would silently
    # behave like the WEAKEST bound, 0, while claiming exhaustiveness)
    if args.explore_bound == "exact":
        bound = None
    else:
        try:
            bound = int(args.explore_bound)
        except ValueError:
            print(f"DPOR FAIL: --explore-bound {args.explore_bound!r}: "
                  f"expected an integer preemption bound or 'exact'")
            return 2
    if bound is not None and bound < 0:
        bound = None
    ranks = tuple(int(r) for r in args.ranks.split(","))
    problems: list[str] = []
    rows = []
    results = analysis.explore_all(ranks, kernel_filter=args.kernel,
                                   preemption_bound=bound)
    if not results:
        problems.append(f"no kernel cases match --kernel {args.kernel!r}")
    pruned = 0
    classes = 0
    for res in results:
        status = "OK" if not res.violations else "VIOLATION"
        extra = "  PRUNED" if res.pruned else ""
        pruned += res.pruned
        classes += res.schedules
        print(f"{res.kernel:<28} ranks={res.n:<2} "
              f"classes={res.schedules:<4} {status}{extra}")
        for v in res.violations:
            print(f"    [{v.check}] {v.message}")
            problems.append(f"{res.kernel}: [{v.check}] {v.message}")
        rows.append({"kernel": res.kernel, "ranks": res.n,
                     "classes": res.schedules, "pruned": res.pruned,
                     "violations": len(res.violations)})

    selftest = fixtures.run_dpor_selftest()
    problems += [f"dpor selftest: {p}" for p in selftest]

    for p in problems:
        print(f"DPOR FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"cases": rows, "selftest_problems": selftest,
                       "problems": problems}, f, indent=1, sort_keys=True)
    if problems:
        return 1
    print(f"dpor OK: {len(results)} cases x {classes} schedule classes "
          f"clean under preemption bound "
          f"{'exact' if bound is None else bound} ({pruned} case(s) "
          f"capped); every order-dependent fixture passes canonically "
          f"and fails under reordering with the reused slot named")
    return 0


def _run_completeness(args) -> int:
    """The cross-subsystem completeness gate (ISSUE 15): the golden
    wiring table (costs + fallback + fault coverage + collective_id per
    registry family) diffed against the live modules, plus the static
    footprint check on every family's DEFAULT tile config at its
    representative serving shape."""
    from triton_distributed_tpu.analysis import completeness, footprint

    problems = completeness.check()
    for fam, spec in sorted(completeness.GOLDEN.items()):
        cid = spec["collective_id"]
        print(f"{fam:<18} costs={','.join(spec['costs']):<40} "
              f"fallback={spec['fallback'][:36]:<38} id={cid}")
    fp_problems = footprint.check_defaults()
    problems += fp_problems
    print(f"default-config footprints: {len(fp_problems)} problem(s)")

    for p in problems:
        print(f"COMPLETENESS FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"problems": problems}, f, indent=1, sort_keys=True)
    if problems:
        return 1
    print("completeness OK: every registry family priced, degradable "
          "(or documented watchdog-only), fault-covered, and uniquely "
          "id'd; every default tile config fits its static VMEM budget")
    return 0


def _run_verify(args) -> int:
    """The default leg: the static protocol verifier over every
    registered kernel case."""
    from triton_distributed_tpu import analysis

    ranks = tuple(int(r) for r in args.ranks.split(","))
    results = analysis.verify_all(ranks=ranks, kernel_filter=args.kernel)
    if not results:
        print(f"no kernel cases match --kernel {args.kernel!r}")
        return 1

    rows = []
    n_violations = 0
    for case, violations in results:
        status = "OK" if not violations else "VIOLATION"
        n_violations += len(violations)
        print(f"{case.name:<28} ranks={case.n:<2} {status}")
        for v in violations:
            print(f"    [{v.check}] {v.message}")
        rows.append({
            "kernel": case.name, "ranks": case.n,
            "violations": [
                {"check": v.check, "message": v.message} for v in violations
            ],
        })
    print(f"\n{len(results)} kernel cases x 4 checks: "
          f"{n_violations} violation(s)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"cases": rows, "violations": n_violations}, f,
                      indent=1, sort_keys=True)
    return 1 if n_violations else 0


def _run_integrity(args) -> int:
    """The data-integrity gate (see module docstring): record-mode
    corruption matrix + scheduler poison cell + verifier selftest."""
    from triton_distributed_tpu import resilience
    from triton_distributed_tpu.resilience import integrity

    rows, cells = resilience.run_integrity_cells(seed=args.seed)
    for row in rows:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<24} {row['fault']:<16} "
              f"{row['outcome'].upper():<9}{named}")
    problems = resilience.verify_matrix(
        rows, kinds=resilience.CORRUPTION_KINDS)

    for cell in cells:
        print(f"{cell['kernel']:<24} {cell['fault']:<16} "
              f"{cell['outcome'].upper():<9} {cell['detail']}")
    problems += resilience.verify_scheduler_matrix(cells)

    selftest = integrity.run_selftest()
    problems += [f"selftest: {p}" for p in selftest]
    resilience.policy._reset_state_for_tests()   # the selftest's probe

    for p in problems:
        print(f"INTEGRITY FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"rows": rows, "scheduler_cells": cells,
                       "problems": problems}, f, indent=1, sort_keys=True)
    if problems:
        return 1
    print("integrity OK: every corruption cell detected with its "
          "(semaphore, chunk, peer) named; poisoned KV page recovered "
          "via preemption-recompute; verifier selftest clean")
    return 0


def _run_quant(args) -> int:
    """The low-precision wire gate (ISSUE 9): (1) the codec selftest
    battery — round-trip error envelopes per wire dtype including the
    all-negative / denormal / absmax-zero edge rows, pack/unpack
    equivalence, the poisoned-scale-sidecar cell (a flipped sidecar byte
    must be checksum-caught, never parity-absorbed), and the
    quantized-reduce verifier's clean/caught pair; (2) the quantized
    collective variants through the static protocol verifier at ranks
    {2,4,8}; (3) both corruption fault classes against every quantized
    kernel case through the record-mode checksum protocol."""
    from triton_distributed_tpu import analysis, resilience
    from triton_distributed_tpu.resilience import integrity

    problems: list[str] = []

    selftest = integrity.run_quant_selftest()
    problems += [f"codec selftest: {p}" for p in selftest]
    print(f"codec selftest: {len(selftest)} problem(s)")

    ranks = tuple(int(r) for r in args.ranks.split(","))
    results = analysis.verify_all(ranks=ranks, kernel_filter="quant")
    rows = []
    for case, violations in results:
        status = "OK" if not violations else "VIOLATION"
        print(f"{case.name:<28} ranks={case.n:<2} {status}")
        for v in violations:
            print(f"    [{v.check}] {v.message}")
            problems.append(f"{case.name}: [{v.check}] {v.message}")
        rows.append({"kernel": case.name, "ranks": case.n,
                     "violations": len(violations)})
    if not results:
        problems.append("no quantized kernel cases registered")

    cells = resilience.run_quant_cells(seed=args.seed)
    for row in cells:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<28} {row['fault']:<16} "
              f"{row['outcome'].upper():<9}{named}")
    problems += resilience.verify_matrix(
        cells, kinds=resilience.CORRUPTION_KINDS)

    for p in problems:
        print(f"QUANT FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"selftest_problems": selftest, "verify": rows,
                       "cells": cells, "problems": problems}, f,
                      indent=1, sort_keys=True)
    if problems:
        return 1
    print("quant OK: codec envelopes hold (edge rows included), a "
          "poisoned scale sidecar is checksum-caught, every quantized "
          "variant verifies at ranks {2,4,8} and detects both "
          "corruption classes")
    return 0


def _run_hier(args) -> int:
    """The hierarchical multi-slice gate (ISSUE 10; see module
    docstring): protocol matrix at the slice layouts, fault cells, and
    the schedule-order selftest on a synthetic 2x4 topology."""
    from triton_distributed_tpu import analysis, resilience
    from triton_distributed_tpu.comm.hierarchical import (
        chunk_schedule, ici_schedule,
    )
    from triton_distributed_tpu.tools.calibrate import LinkCalibration

    problems: list[str] = []

    # 1: the two-level protocol matrix at {2x2, 2x4, 4x2} plus the
    # scheduled-emission flat A2A variant at ranks {2,4,8}
    for filt in ("hier", "scheduled"):
        results = analysis.verify_all(ranks=(2, 4, 8), kernel_filter=filt)
        if not results:
            problems.append(f"no kernel cases match filter {filt!r}")
        for case, violations in results:
            status = "OK" if not violations else "VIOLATION"
            print(f"{case.name:<28} ranks={case.n:<2} {status}")
            for v in violations:
                print(f"    [{v.check}] {v.message}")
                problems.append(f"{case.name}: [{v.check}] {v.message}")

    # 2: the fault cells over every hierarchical kernel case — the
    # dropped-inter-slice-credit class rides drop_notify/stale_credit
    # landing on the dcn semaphores and must be DETECTED
    cells = resilience.run_hier_cells(seed=args.seed)
    for row in cells:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<26} {row['fault']:<16} "
              f"{row['outcome'].upper():<9}{named}")
    problems += resilience.verify_matrix(cells)
    dcn_detected = [r for r in cells
                    if r["outcome"] == "detected"
                    and any("dcn" in s for s in r["named"])]
    if not dcn_detected:
        problems.append(
            "no fault cell detection named an inter-slice (dcn) "
            "semaphore — the dropped-inter-slice-credit class is not "
            "being exercised")

    # 3: schedule-order selftest on a synthetic 2x4 topology
    cal = LinkCalibration(ici_gbps=186.0, ici_hop_us=1.4, dcn_gbps=6.25,
                          dcn_hop_us=20.0, device_kind="TPU v5e",
                          n_devices=8, num_slices=2, chips_per_slice=4)
    sched = chunk_schedule(2, 4, cal)
    print(f"schedule(2x4, dcn-slow): {sched}")
    k = len([g for g in sched if g[0] != 0])
    if not all(g[0] != 0 for g in sched[:k]):
        problems.append(f"schedule {sched}: a DCN-bound group is not "
                        f"ahead of every ICI-bound group")
    if sched[-1] != (0, 0):
        problems.append(f"schedule {sched}: the self group must be last")
    ici_part = [g[1] for g in sched if g[0] == 0 and g[1] != 0]
    if ici_part != list(ici_schedule(4))[:-1]:
        problems.append(f"schedule {sched}: ICI groups not farthest-first "
                        f"({ici_part} != {list(ici_schedule(4))[:-1]})")
    flipped = chunk_schedule(2, 4, LinkCalibration(
        ici_gbps=6.25, ici_hop_us=20.0, dcn_gbps=186.0, dcn_hop_us=1.4,
        num_slices=2, chips_per_slice=4))
    k2 = len([g for g in flipped if g[0] == 0 and g != (0, 0)])
    if not all(g[0] == 0 for g in flipped[:k2]):
        problems.append(
            f"schedule {flipped}: with the ICI measured slower, "
            f"ICI-bound groups must launch first — the order must track "
            f"the CALIBRATION, not a hard-coded class")

    for p in problems:
        print(f"HIER FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"cells": cells, "schedule_2x4": sched,
                       "problems": problems}, f, indent=1, sort_keys=True,
                      default=str)
    if problems:
        return 1
    detected = sum(r["outcome"] == "detected" for r in cells)
    survived = sum(r["outcome"] == "survived" for r in cells)
    print(f"hier OK: two-level protocols verify clean at slice layouts "
          f"{{2x2, 2x4, 4x2}}; {len(cells)} fault cells ({detected} "
          f"detected / {survived} survived) incl. inter-slice credit "
          f"drops named; schedule order tracks the calibrated topology")
    return 0


def _run_all(args) -> int:
    """One aggregate CI entry: every gate, a summary table, one exit
    code (the max of the legs; a crashed leg counts as 1)."""
    import argparse as _ap
    import traceback

    def sub(**kw):
        d = dict(vars(args))
        d.update(kw, all_gates=False, json=None)
        return _ap.Namespace(**d)

    legs = [
        ("verify", lambda: _run_verify(sub())),
        ("dpor", lambda: _run_dpor(sub())),
        ("completeness", lambda: _run_completeness(sub())),
        ("faults", lambda: _run_faults(sub())),
        ("timeline", lambda: _run_timeline(sub())),
        ("serve", lambda: _run_serve(sub())),
        ("history", lambda: _run_history(sub())),
        # legs are deliberately self-contained: --faults and --serve
        # overlap the integrity leg's corruption/poison cells (seconds
        # of redundant work), but deduping would couple the legs' rng
        # states so `--all`'s integrity leg no longer reproduced a
        # standalone `--integrity` run
        ("integrity", lambda: _run_integrity(sub())),
        ("quant", lambda: _run_quant(sub())),
        ("hier", lambda: _run_hier(sub())),
        ("handoff", lambda: _run_handoff(sub())),
        ("persistent", lambda: _run_persistent(sub())),
        ("trace", lambda: _run_trace(sub())),
        ("profile", lambda: _run_profile(sub())),
        ("pages", lambda: _run_pages(sub())),
        ("fleet", lambda: _run_fleet(sub())),
        ("fleetobs", lambda: _run_fleetobs(sub())),
        ("regress", lambda: _run_regress(sub())),
    ]
    results = []
    for name, fn in legs:
        print(f"\n=== tdt_lint --{name} " + "=" * max(0, 50 - len(name)))
        try:
            rc = int(fn())
        except Exception:
            traceback.print_exc()
            rc = 1
        results.append((name, rc))
    print("\n=== summary " + "=" * 50)
    for name, rc in results:
        print(f"{name:<12} {'OK' if rc == 0 else f'FAIL (rc {rc})'}")
    worst = max(rc for _, rc in results)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"legs": dict(results), "rc": worst}, f,
                      indent=1, sort_keys=True)
    return worst


def _run_pages(args) -> int:
    """The page-lifetime ownership gate (docs/static_analysis.md "Page
    lifetime checking"): (1) the DPOR explorer over every clean
    two-tier scenario — all schedule classes of the prefill/router/
    decode/scrubber interleaving leak-free and lifetime-safe, never
    pruned; (2) the seeded-bad fixture selftest in both directions
    (clean quiet, every planted double-free / scrub-under-reader /
    leak-on-abort / unverified-adopt / refcount-underflow caught with
    the page id and violating transition named); (3) a static replay of
    the fault matrix's serving cells — every scheduler and handoff
    cell's recorded page trace re-checked by the ownership state
    machine, so each cell's "zero leaked pages" claim is discharged
    structurally, not just by the free-list counter."""
    from triton_distributed_tpu.analysis import fixtures
    from triton_distributed_tpu.analysis.pages import (
        explore_pages, two_tier_scenarios,
    )
    from triton_distributed_tpu.resilience import matrix

    problems: list[str] = []
    scen_rows = []
    classes = 0
    for name, scenario in two_tier_scenarios():
        res = explore_pages(name, scenario)
        classes += res.schedules
        status = "OK" if not res.violations else "VIOLATION"
        extra = "  PRUNED" if res.pruned else ""
        print(f"{name:<28} actors={len(res.actors):<2} "
              f"classes={res.schedules:<4} {status}{extra}")
        for v in res.violations:
            print(f"    [{v.check}] {v.message}")
            problems.append(f"{name}: [{v.check}] {v.message}")
        if res.pruned:
            problems.append(f"{name}: clean-scenario exploration was "
                            f"pruned — the sweep must be exhaustive")
        scen_rows.append({"scenario": name, "actors": len(res.actors),
                          "classes": res.schedules, "pruned": res.pruned,
                          "violations": len(res.violations)})

    selftest = fixtures.run_page_selftest()
    problems += [f"page selftest: {p}" for p in selftest]

    sched_rows = matrix.run_scheduler_matrix(seed=args.seed)
    hand_rows = matrix.run_handoff_matrix(seed=args.seed)
    events = 0
    for row in sched_rows + hand_rows:
        key = f"{row['kernel']} x {row['fault']}/{row['leg']}"
        ev = row.get("lifecycle_events", 0)
        vs = row.get("lifecycle_violations", [])
        events += ev
        print(f"{key:<44} events={ev:<4} "
              f"{'clean' if not vs and ev else 'VIOLATION'}")
        if not ev:
            problems.append(f"{key}: lifecycle recorder saw zero page "
                            f"events — interception unwired")
        problems += [f"{key}: {v}" for v in vs]

    for p in problems:
        print(f"PAGES FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"scenarios": scen_rows,
                       "selftest_problems": selftest,
                       "matrix_events": events,
                       "problems": problems}, f, indent=1,
                      sort_keys=True)
    if problems:
        return 1
    print(f"pages OK: {len(scen_rows)} two-tier scenarios x {classes} "
          f"schedule classes leak-free and lifetime-safe; every seeded "
          f"lifecycle fixture caught with the page and transition "
          f"named; {len(sched_rows) + len(hand_rows)} fault-matrix "
          f"cells statically re-verified over {events} recorded page "
          f"events")
    return 0


def _run_faults(args) -> int:
    from triton_distributed_tpu import resilience

    rows = resilience.run_matrix(seed=args.seed)
    for row in rows:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<24} {row['fault']:<14} "
              f"{row['outcome'].upper():<9}{named}")
    problems = resilience.verify_matrix(rows)
    detected = sum(r["outcome"] == "detected" for r in rows)
    survived = sum(r["outcome"] == "survived" for r in rows)
    print(f"\n{len(rows)} injections: {detected} detected, "
          f"{survived} survived, {len(problems)} problem(s)")
    for p in problems:
        print(f"FAULT MATRIX FAIL: {p}")
    if args.json:
        import json as _json

        with open(args.json, "w") as f:
            _json.dump({"rows": rows, "problems": problems}, f,
                       indent=1, sort_keys=True)
    return 1 if problems else 0


def _run_serve(args) -> int:
    """The scheduler overload smoke (see module docstring): trace leg
    then matrix leg; every problem printed with a SERVE FAIL prefix."""
    from triton_distributed_tpu import resilience
    from triton_distributed_tpu import serve
    from triton_distributed_tpu.resilience.faults import RankAborted

    problems: list[str] = []

    # leg 1: seeded 64-request open-loop trace, ~2x page-budget
    # overcommit, one rank abort injected mid-decode
    class Inject:
        fired = 0

        def __call__(self, step):
            if step == 9 and not self.fired:
                self.fired = 1
                raise RankAborted(1, step)

    inj = Inject()
    backend = serve.SimBackend(slots=4, page_size=4, pool_pages=33,
                               max_length=64, step_hook=inj)
    sched = serve.Scheduler(backend, serve.SchedulerConfig(
        max_queue_depth=64))
    arrivals = serve.synthetic_trace(args.seed, 64,
                                     mean_interarrival_steps=0.5,
                                     prompt_len=(2, 12), max_new=(2, 12))
    report = serve.replay(sched, arrivals, max_steps=20_000)
    print(f"serve trace: {len(report.requests)} requests -> "
          f"{len(report.completed)} completed, {len(report.failed)} "
          f"failed, {len(report.shed)} shed; {sched.preemptions} "
          f"preemption(s), peak pool occupancy "
          f"{report.peak_pool_occupancy:.2f}, {report.steps} steps, "
          f"leaked pages {report.leaked_pages}, monotone drain "
          f"{report.drain_monotone}")
    problems += [f"trace: {p}" for p in report.problems()]
    if not inj.fired:
        problems.append("trace: the rank-abort injection never fired "
                        "(decode never reached step 9?)")
    elif len(report.failed) != 1:
        problems.append(
            f"trace: expected exactly the injected victim to fail, got "
            f"{len(report.failed)} failure(s): "
            f"{[(r.req_id, r.error) for r in report.failed]}")
    elif "RankAborted" not in (report.failed[0].error or ""):
        problems.append(f"trace: victim error does not name the fault: "
                        f"{report.failed[0].error!r}")

    # leg 2: the scheduler cells of the fault matrix
    rows = resilience.run_scheduler_matrix(seed=args.seed)
    for row in rows:
        print(f"{row['kernel']:<20} {row['fault']:<12} {row['leg']:<8} "
              f"{row['outcome'].upper():<10} {row['detail']}")
    problems += resilience.verify_scheduler_matrix(rows)

    for p in problems:
        print(f"SERVE FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "trace": {
                    "requests": len(report.requests),
                    "completed": len(report.completed),
                    "failed": len(report.failed),
                    "shed": len(report.shed),
                    "preemptions": sched.preemptions,
                    "leaked_pages": report.leaked_pages,
                    "drain_monotone": report.drain_monotone,
                },
                "cells": rows,
                "problems": problems,
            }, f, indent=1, sort_keys=True, default=str)
    if problems:
        return 1
    print("serve OK: overload trace drained with zero leaked pages and "
          "per-request isolation; scheduler fault cells all "
          "detected-or-survived")
    return 0


def _two_tier_replay(seed: int, faults):
    """ONE home for the seeded two-tier gate harness (shared by
    ``--handoff`` and ``--trace``): prefill tier -> ModeledDCN with the
    given fault plan -> decode tier through the REAL router, 24
    requests driven open-loop until idle.  Returns
    ``(router, plane, requests)``; the caller owns breaker hygiene."""
    from triton_distributed_tpu import serve

    pre = serve.Scheduler(
        serve.SimBackend(slots=4, page_size=4, pool_pages=33,
                         max_length=64),
        serve.SchedulerConfig(max_queue_depth=64, prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=4, page_size=4, pool_pages=49,
                         max_length=64),
        serve.SchedulerConfig(max_queue_depth=64))
    plane = serve.HandoffPlane(
        dcn_channel=serve.ModeledDCN(faults=list(faults), seed=seed))
    router = serve.DisaggRouter(pre, dec, plane=plane)
    arrivals = serve.synthetic_trace(seed, 24,
                                     mean_interarrival_steps=0.5,
                                     prompt_len=(2, 12), max_new=(2, 10))
    idx = 0
    pending = sorted(arrivals, key=lambda a: (a.step, a.request.req_id))
    for _ in range(20_000):
        while idx < len(pending) and \
                pending[idx].step <= pre.steps:
            router.submit(pending[idx].request)
            idx += 1
        if idx >= len(pending) and router.step().idle:
            break
        elif idx < len(pending):
            router.step()
    return router, plane, [a.request for a in arrivals]


def _run_handoff(args) -> int:
    """The disaggregated-serving gate (see module docstring): a seeded
    two-tier replay with three wire faults injected, then the handoff
    fault cells."""
    from triton_distributed_tpu import resilience, serve

    problems: list[str] = []

    # leg 1: two-tier replay — 24 requests through the router with a
    # transfer DROP (every attempt: the ladder must bottom out to
    # re-prefill), a CORRUPT page (first attempt: the retry recovers),
    # and a prefill-slice ABORT mid-handoff
    resilience.reset_breaker(serve.HANDOFF_OP)
    router, plane, reqs = _two_tier_replay(args.seed, [
        serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 2),
        serve.WireFault(serve.HandoffFault.CORRUPT_PAGE, 5, attempts=1),
        serve.WireFault(serve.HandoffFault.PREFILL_ABORT, 8),
    ])
    pre = router.prefill
    done = [r for r in reqs if r.state is serve.RequestState.DONE]
    failed = [r for r in reqs if r.state is serve.RequestState.FAILED]
    nonterminal = [r for r in reqs if not r.done]
    parity_bad = [r.req_id for r in done
                  if r.tokens != pre.backend.expected_tokens(r)]
    print(f"handoff trace: {len(reqs)} requests -> {len(done)} "
          f"completed, {len(failed)} failed; {router.handoffs} "
          f"handoffs, {router.colocated} colocated, "
          f"{router.reprefills} re-prefills, {router.aborts} aborts, "
          f"{plane.retries} retries, {len(plane.corruptions)} "
          f"corruption(s) named, leaked pages {router.leaked_pages()}")
    if nonterminal:
        problems.append(f"trace: {len(nonterminal)} request(s) never "
                        f"terminal: {[r.req_id for r in nonterminal]}")
    if failed:
        problems.append(f"trace: {len(failed)} request(s) FAILED — "
                        f"every faulted transfer must recover via "
                        f"retry or re-prefill: "
                        f"{[(r.req_id, r.error) for r in failed]}")
    if parity_bad:
        problems.append(f"trace: token parity broken vs the colocated "
                        f"golden for request(s) {parity_bad}")
    if router.leaked_pages():
        problems.append(f"trace: {router.leaked_pages()} page(s) "
                        f"leaked across the tiers")
    if plane.dcn.drops < 1 or router.reprefills < 1:
        problems.append(f"trace: the drop injection never exercised "
                        f"the re-prefill fallback (drops="
                        f"{plane.dcn.drops}, reprefills="
                        f"{router.reprefills})")
    if not plane.corruptions:
        problems.append("trace: the corrupt-page injection was never "
                        "named by the stamp verify")
    if router.aborts < 1:
        problems.append("trace: the prefill-slice abort never fired")
    resilience.reset_breaker(serve.HANDOFF_OP)

    # leg 2: the handoff fault cells
    rows = resilience.run_handoff_matrix(seed=args.seed)
    for row in rows:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<20} {row['fault']:<24} "
              f"{row['outcome'].upper():<10}{named}")
    problems += resilience.verify_handoff_matrix(rows)

    for p in problems:
        print(f"HANDOFF FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "trace": {
                    "requests": len(reqs), "completed": len(done),
                    "failed": len(failed),
                    "handoffs": router.handoffs,
                    "colocated": router.colocated,
                    "reprefills": router.reprefills,
                    "aborts": router.aborts,
                    "leaked_pages": router.leaked_pages(),
                },
                "cells": rows, "problems": problems,
            }, f, indent=1, sort_keys=True, default=str)
    if problems:
        return 1
    print("handoff OK: two-tier replay drained with zero leaked pages "
          "on both tiers, every faulted request completed via "
          "retry/re-prefill with token parity; all handoff fault "
          "cells detected-or-survived")
    return 0


def _run_fleet(args) -> int:
    """The fleet-tier gate (ISSUE 18; see module docstring): a seeded
    N=4 replay with one replica lost mid-decode and one flapping into
    quarantine, then the fleet fault cells."""
    import random

    from triton_distributed_tpu import resilience, serve
    from triton_distributed_tpu.resilience.faults import RankAborted

    _FLEET_IDS = ("p0", "p1", "d0", "d1")

    def reset_replica_breakers():
        for rid in _FLEET_IDS:
            resilience.reset_breaker(serve.replica_breaker_name(rid))
        resilience.reset_breaker(serve.HANDOFF_OP)

    problems: list[str] = []
    rng = random.Random(args.seed)
    reset_replica_breakers()

    # leg 1: N=4 replay — 12 requests over 2 prefill + 2 decode
    # replicas; d1 FLAPS (RankAborted on every dispatch in a step
    # window — its sticky replica:d1 breaker must walk open, drain,
    # evict) and d0 is LOST mid-decode (every resident re-prefilled on
    # a survivor, original clock carried)
    class _Flap:
        def __init__(self, first, last):
            self.first, self.last, self.fired = first, last, 0

        def __call__(self, step):
            if self.first <= step <= self.last:
                self.fired += 1
                raise RankAborted(0, step)

    inj = _Flap(3, 10)
    replicas = []
    for rid in ("p0", "p1"):
        replicas.append(serve.Replica(
            rid,
            serve.Scheduler(
                serve.SimBackend(slots=3, page_size=4, pool_pages=24,
                                 max_length=64),
                serve.SchedulerConfig(max_queue_depth=32,
                                      prefill_only=True)),
            "prefill"))
    for rid in ("d0", "d1"):
        replicas.append(serve.Replica(
            rid,
            serve.Scheduler(
                serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                                 max_length=64,
                                 step_hook=inj if rid == "d1" else None),
                serve.SchedulerConfig(max_queue_depth=32)),
            "decode"))
    router = serve.FleetRouter(
        replicas,
        plane=serve.HandoffPlane(dcn_channel=serve.ModeledDCN(
            seed=rng.randrange(1 << 16))),
        # a request can fault TWICE here (flap off d1, then lose d0 it
        # landed on, then bounce off d1 again before its breaker
        # opens): give the ladder headroom above the default cap
        config=serve.FleetConfig(flap_threshold=3,
                                 max_failovers_per_request=4,
                                 probe_interval_steps=1 << 30))
    reqs = [
        serve.Request(prompt=tuple(rng.randrange(1, 90)
                                   for _ in range(rng.randint(2, 6))),
                      max_new_tokens=rng.randint(6, 10))
        for _ in range(12)
    ]
    from triton_distributed_tpu.analysis import pages as _pages

    lost_id = None
    moved: list[int] = []
    with _pages.record() as rec:
        for r in reqs:
            router.submit(r)
        for _ in range(600):
            router.step()
            d0 = next(rep for rep in router.replicas
                      if rep.replica_id == "d0")
            if lost_id is None and any(
                    s is not None
                    and s.request.state is serve.RequestState.DECODE
                    for s in d0.scheduler.slots):
                lost_id = "d0"
                moved = router.lose_replica(
                    "d0", reason="injected mid-decode replica loss")
                break
        router.run_until_idle(max_steps=4000)
    backend = router.replicas[0].scheduler.backend
    done = [r for r in reqs if r.state is serve.RequestState.DONE]
    nonterminal = [r for r in reqs if not r.done]
    parity_bad = [r.req_id for r in done
                  if r.tokens != backend.expected_tokens(r)]
    quarantined = [rep.replica_id for rep in router.replicas
                   if rep.quarantined]
    leaked_by = {rep.replica_id: rep.scheduler.pool.used_pages
                 for rep in router.replicas if rep.scheduler.pool.used_pages}
    lifecycle = [str(v) for v in _pages.check_recorder(rec, label="fleet")]
    print(f"fleet replay: {len(reqs)} requests -> {len(done)} "
          f"completed; replica {lost_id} lost with {len(moved)} "
          f"resident(s), d1 flapped {inj.fired}x, quarantined="
          f"{quarantined}, {router.failovers} failovers, "
          f"{router.reprefills} re-prefills, {router.handoffs} "
          f"handoffs, leaked pages {router.leaked_pages()}")
    if lost_id is None or not moved:
        problems.append(f"replay: the replica-loss injection never "
                        f"landed mid-decode (lost={lost_id}, "
                        f"moved={len(moved)})")
    if inj.fired < 3:
        problems.append(f"replay: the flap window only fired "
                        f"{inj.fired}x — below the breaker threshold")
    if nonterminal:
        problems.append(f"replay: {len(nonterminal)} request(s) never "
                        f"terminal: {[r.req_id for r in nonterminal]}")
    if len(done) != len(reqs):
        problems.append(f"replay: {len(reqs) - len(done)} faulted "
                        f"request(s) did not complete on a survivor: "
                        f"{[(r.req_id, r.state.name, r.error) for r in reqs if r.state is not serve.RequestState.DONE]}")
    if parity_bad:
        problems.append(f"replay: token parity broken vs the "
                        f"deterministic golden for request(s) "
                        f"{parity_bad}")
    if quarantined != ["d1"]:
        problems.append(f"replay: exactly the flapping replica must be "
                        f"quarantine-evicted — expected ['d1'], got "
                        f"{quarantined}")
    if router.lost_replicas != ["d0"]:
        problems.append(f"replay: lost_replicas must name exactly the "
                        f"lost replica — got {router.lost_replicas}")
    if leaked_by:
        problems.append(f"replay: page(s) leaked per replica: "
                        f"{leaked_by}")
    if lifecycle:
        problems.append(f"replay: page-lifecycle violations: "
                        f"{lifecycle}")
    reset_replica_breakers()

    # leg 2: the fleet fault cells
    rows = resilience.run_fleet_matrix(seed=args.seed)
    for row in rows:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<20} {row['fault']:<26} "
              f"{row['outcome'].upper():<10}{named}")
    problems += resilience.verify_fleet_matrix(rows)

    for p in problems:
        print(f"FLEET FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "replay": {
                    "requests": len(reqs), "completed": len(done),
                    "lost": lost_id, "moved": len(moved),
                    "flaps": inj.fired, "quarantined": quarantined,
                    "failovers": router.failovers,
                    "reprefills": router.reprefills,
                    "leaked_pages": router.leaked_pages(),
                },
                "cells": rows, "problems": problems,
            }, f, indent=1, sort_keys=True, default=str)
    if problems:
        return 1
    print("fleet OK: N=4 replay survived one replica loss mid-decode "
          "and one flap into quarantine — every faulted request "
          "completed on a survivor with token parity, exactly the "
          "flapping replica evicted, zero leaked pages on every "
          "replica; all fleet fault cells detected-or-survived")
    return 0


def _run_fleetobs(args) -> int:
    """The fleet-observability gate (ISSUE 19; see module docstring):
    the ``--fleet`` replay shape re-run with ``TDT_FLEET_OBS`` armed —
    (1) every actuation ledgered, with the per-kind counts reconciling
    against the router's own counters and the quarantine-drain
    decision naming an exemplar trace id that resolves in the retained
    ring; (2) the ledger's rotated JSONL segments round-trip through
    ``obs.history.load_decision_records``; (3) the fleet-merged
    latency sketches reconcile EXACTLY with the union stream (the tee
    federation is lossless); (4) the decision-coverage golden
    discharges statically in both directions; (5) the fleet-anomaly
    selftest passes both directions."""
    import random
    import tempfile

    from triton_distributed_tpu import obs, resilience, serve
    from triton_distributed_tpu.analysis import completeness
    from triton_distributed_tpu.obs import decisions, fleet_stats, history
    from triton_distributed_tpu.obs import request_trace as rtrace
    from triton_distributed_tpu.resilience.faults import RankAborted

    _FLEET_IDS = ("p0", "p1", "d0", "d1")

    def reset_replica_breakers():
        for rid in _FLEET_IDS:
            resilience.reset_breaker(serve.replica_breaker_name(rid))
        resilience.reset_breaker(serve.HANDOFF_OP)

    problems: list[str] = []
    rng = random.Random(args.seed)
    reset_replica_breakers()

    prev_obs = obs.enabled()
    prev_dec = decisions.enabled()
    prev_fs = fleet_stats.enabled()
    obs.enable(True)
    prev_trace = rtrace.enable(True)
    decisions.enable(True)
    fleet_stats.enable(True)
    prev_ledger = None
    prev_fleet = fleet_stats.current()
    rtrace.RING.clear()
    obs.serve_stats.STATS.reset()
    tmp = tempfile.mkdtemp(prefix="tdt_fleetobs_")
    prev_ledger = decisions.install(
        decisions.DecisionLedger(cap=512, out_dir=tmp))
    try:
        # the --fleet replay, armed: d1 flaps into quarantine, d0 is
        # lost mid-decode — every actuation below must ledger
        class _Flap:
            def __init__(self, first, last):
                self.first, self.last, self.fired = first, last, 0

            def __call__(self, step):
                if self.first <= step <= self.last:
                    self.fired += 1
                    raise RankAborted(0, step)

        inj = _Flap(3, 10)
        replicas = []
        for rid in ("p0", "p1"):
            replicas.append(serve.Replica(
                rid,
                serve.Scheduler(
                    serve.SimBackend(slots=3, page_size=4, pool_pages=24,
                                     max_length=64),
                    serve.SchedulerConfig(max_queue_depth=32,
                                          prefill_only=True)),
                "prefill"))
        for rid in ("d0", "d1"):
            replicas.append(serve.Replica(
                rid,
                serve.Scheduler(
                    serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                                     max_length=64,
                                     step_hook=inj if rid == "d1"
                                     else None),
                    serve.SchedulerConfig(max_queue_depth=32)),
                "decode"))
        router = serve.FleetRouter(
            replicas,
            plane=serve.HandoffPlane(dcn_channel=serve.ModeledDCN(
                seed=rng.randrange(1 << 16))),
            config=serve.FleetConfig(flap_threshold=3,
                                     max_failovers_per_request=4,
                                     probe_interval_steps=1 << 30))
        if router.fleet_stats is None:
            problems.append("FleetRouter attached no federation plane "
                            "with TDT_FLEET_OBS armed")
        reqs = [
            serve.Request(prompt=tuple(rng.randrange(1, 90)
                                       for _ in range(rng.randint(2, 6))),
                          max_new_tokens=rng.randint(6, 10))
            for _ in range(12)
        ]
        for r in reqs:
            router.submit(r)
        lost_id = None
        for _ in range(600):
            router.step()
            d0 = next(rep for rep in router.replicas
                      if rep.replica_id == "d0")
            if lost_id is None and any(
                    s is not None
                    and s.request.state is serve.RequestState.DECODE
                    for s in d0.scheduler.slots):
                lost_id = "d0"
                router.lose_replica(
                    "d0", reason="injected mid-decode replica loss")
                break
        router.run_until_idle(max_steps=4000)
        nonterminal = [r.req_id for r in reqs if not r.done]
        if lost_id is None:
            problems.append("replay: the replica-loss injection never "
                            "landed mid-decode")
        if nonterminal:
            problems.append(f"replay: {len(nonterminal)} request(s) "
                            f"never terminal: {nonterminal}")

        led = decisions.ledger()
        counts = {} if led is None else led.counts()
        print(f"fleetobs replay: {len(reqs)} requests, "
              f"{0 if led is None else led.total} decisions ledgered "
              f"{dict(sorted(counts.items()))}")
        if led is None:
            problems.append("armed replay produced no decision ledger")
            raise _FleetObsBail()

        # leg 1: every actuation ledgered — per-kind counts reconcile
        # against the router's own counters (the ledger IS the
        # actuation stream, not a sample of it)
        admissions = sum(counts.get(k, 0) for k in
                        ("route", "affinity_hit", "affinity_redirect",
                         "shed"))
        pairs = [
            ("admission decisions", admissions, len(reqs)),
            ("failover", counts.get("failover", 0), router.failovers),
            ("failover_shed", counts.get("failover_shed", 0),
             router.failover_shed),
            ("reprefill", counts.get("reprefill", 0), router.reprefills),
            ("replica_lost", counts.get("replica_lost", 0),
             len(router.lost_replicas)),
            ("quarantine_evict", counts.get("quarantine_evict", 0),
             len(router.quarantined_history)),
        ]
        for label, got, want in pairs:
            if got != want:
                problems.append(f"ledger: {label} count {got} != the "
                                f"router's {want}")
        # colocations: the dedicated colocate decisions plus every
        # admission the ledger itself says landed on a decode replica
        # (inputs carried verbatim makes this derivable)
        routed_decode = sum(
            1 for k in ("route", "affinity_hit", "affinity_redirect")
            for rec in led.query(kind=k)
            if rec.inputs.get("role") == "decode")
        if counts.get("colocate", 0) + routed_decode != router.colocated:
            problems.append(
                f"ledger: colocate {counts.get('colocate', 0)} + "
                f"decode-role admissions {routed_decode} != the "
                f"router's colocated {router.colocated}")
        drains = led.query(kind="quarantine_drain")
        if not drains:
            problems.append("ledger: the flap walked quarantine but no "
                            "quarantine_drain decision landed")
        for rec in drains:
            ex = rec.inputs.get("exemplar")
            if ex is None:
                problems.append(f"ledger: quarantine_drain for "
                                f"{rec.replica} names no exemplar "
                                f"trace id")
            elif rtrace.RING.get(ex) is None:
                problems.append(f"ledger: quarantine_drain exemplar "
                                f"{ex!r} does not resolve to a "
                                f"retained trace")
            else:
                print(f"quarantine_drain({rec.replica}) exemplar -> "
                      f"{ex} (retained)")

        # leg 2: the rotated JSONL segments round-trip the ring
        disk = history.load_decision_records(tmp)
        ring = [r.to_dict() for r in led.tail()]
        key = lambda d: (d.get("seq"), d.get("kind"), d.get("step"),
                         d.get("replica"))
        if [key(d) for d in disk] != [key(d) for d in ring]:
            problems.append(
                f"persistence: {len(disk)} JSONL record(s) do not "
                f"round-trip the {len(ring)}-record ring")
        else:
            print(f"persistence: {len(disk)} JSONL records round-trip "
                  f"the ring via load_decision_records")

        # leg 3: the fleet-merged sketches reconcile EXACTLY with the
        # union stream — the tee forwards every observation, so the
        # merge is lossless, not approximate (handoff_ms is plane-fed,
        # union-only by design)
        fs = router.fleet_stats
        union = obs.serve_stats.STATS
        for name in fleet_stats.SKETCH_NAMES:
            merged = fs.merged(name)
            ref = getattr(union, name)
            if name == "handoff_ms":
                if merged.count > ref.count:
                    problems.append(f"merge: {name} merged count "
                                    f"{merged.count} exceeds the union "
                                    f"{ref.count}")
                continue
            if merged.count != ref.count:
                problems.append(f"merge: {name} merged count "
                                f"{merged.count} != union {ref.count}")
                continue
            for q in obs.serve_stats.SERVE_QUANTILES:
                m, u = merged.quantile(q), ref.quantile(q)
                if m != u:
                    problems.append(f"merge: {name} p{int(q * 100)} "
                                    f"merged {m!r} != union {u!r}")
        print(f"merge: request_ms p99 fleet-merged "
              f"{fs.merged('request_ms').quantile(0.99):.3f} ms == "
              f"union ({union.request_ms.count} observations)")

        # leg 4: the decision-coverage golden, both directions
        problems += [str(p) for p in
                     completeness.check_decision_coverage()]

        # leg 5: the fleet-anomaly selftest, both directions
        problems += fleet_stats.selftest(args.seed)
    except _FleetObsBail:
        pass
    finally:
        reset_replica_breakers()
        decisions.install(prev_ledger)
        decisions.enable(prev_dec)
        fleet_stats.install(prev_fleet)
        fleet_stats.enable(prev_fs)
        rtrace.RING.clear()
        rtrace.enable(prev_trace)
        obs.serve_stats.STATS.reset()
        obs.enable(prev_obs)

    for p in problems:
        print(f"FLEETOBS FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"problems": problems}, f, indent=1,
                      sort_keys=True, default=str)
    if problems:
        return 1
    print("fleetobs OK: every actuation ledgered with counts "
          "reconciling against the router, the quarantine decision "
          "names a retained exemplar trace, the JSONL segments "
          "round-trip, the fleet merge is lossless vs the union "
          "stream, the coverage golden discharges both directions, "
          "and the anomaly selftest passes both directions")
    return 0


class _FleetObsBail(Exception):
    """Early exit for --fleetobs when the armed replay produced no
    ledger (everything downstream would mask that one failure)."""


def _run_regress(args) -> int:
    """The regression-forensics gate (ISSUE 20; see module docstring):
    (1) the seeded both-direction ``obs.diff`` selftest — a healthy
    window vs a wire-inflated replay of itself must attribute the
    delta to the injected family/phase with the stall triple and a
    resolving exemplar under the exactness contract, an
    identical-capture diff must rank nothing, and the fast-vs-slow
    trace pairing must rank the inflated phase first; (2) the
    direction-coverage golden — every bench metric classifies under a
    named ``DIRECTION_RULES`` row, no dead rules or allowlist rows."""
    from triton_distributed_tpu.analysis import completeness
    from triton_distributed_tpu.obs import diff

    problems = diff.selftest(args.seed)
    problems += [f"direction coverage: {p}"
                 for p in completeness.check_direction_coverage()]
    for p in problems:
        print(f"REGRESS FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"problems": problems}, f, indent=1,
                      sort_keys=True, default=str)
    if problems:
        return 1
    print("regress OK: seeded wire inflation attributed to the "
          "injected family/phase with the dominant stall and a "
          "resolving exemplar (exact decomposition), identical "
          "captures rank nothing, the slow-trace pairing ranks the "
          "inflated phase first, and every bench metric classifies "
          "under a named direction rule with no dead rows")
    return 0


def _run_trace(args) -> int:
    """The request-tracing gate (ISSUE 14; see module docstring): a
    seeded two-tier replay with a transfer drop, under TDT_TRACE —
    gapless chains, attributor exactness, exemplar resolution, and the
    faulted request's ladder rungs named."""
    from triton_distributed_tpu import obs, resilience, serve
    from triton_distributed_tpu.obs import request_trace as rtrace

    problems: list[str] = []
    prev_obs = obs.enabled()
    obs.enable(True)
    prev_trace = rtrace.enable(True)
    rtrace.RING.clear()
    obs.serve_stats.STATS.reset()
    resilience.reset_breaker(serve.HANDOFF_OP)
    try:
        # transfer #2 drops on EVERY attempt: the ladder must bottom
        # out to the re-prefill fallback with every rung on the trace
        # (the --handoff harness, one home: _two_tier_replay)
        router, plane, reqs = _two_tier_replay(args.seed, [
            serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 2),
        ])
        nonterminal = [r for r in reqs if not r.done]
        if nonterminal:
            problems.append(f"{len(nonterminal)} request(s) never "
                            f"terminal: "
                            f"{[r.req_id for r in nonterminal]}")
        # leg 1: every request traced with a gapless chain whose
        # attributor phases sum exactly to end-to-end latency
        worst_gap = 0.0
        for r in reqs:
            tr = r.trace
            if tr is None:
                problems.append(f"request {r.req_id} carries no trace "
                                f"with TDT_TRACE armed")
                continue
            problems += rtrace.verify_chain(tr)
            att = rtrace.attribute_request(tr)
            total = sum(p["exposed_ms"] for p in att["phases"].values())
            worst_gap = max(worst_gap, abs(total - att["e2e_ms"]))
            if abs(total - att["e2e_ms"]) > 1e-6:
                problems.append(
                    f"trace {tr.trace_id}: attributor phases sum to "
                    f"{total:.6f} ms but e2e is {att['e2e_ms']:.6f} ms "
                    f"— {att['gap_ms']:.6f} ms unaccounted")
        print(f"trace replay: {len(reqs)} requests, {router.handoffs} "
              f"handoffs, {router.reprefills} re-prefills, "
              f"{len(rtrace.RING)} traces retained, worst attribution "
              f"gap {worst_gap * 1e3:.3f} us")
        # leg 2: p99 exemplar ids resolve to retained traces
        stats = obs.serve_stats.STATS
        for name, sketch in (("ttft_ms", stats.ttft_ms),
                             ("request_ms", stats.request_ms)):
            ex = sketch.exemplar(0.99)
            if ex is None:
                problems.append(f"{name} p99 bucket carries no exemplar")
            elif rtrace.RING.get(ex) is None:
                problems.append(f"{name} p99 exemplar {ex!r} does not "
                                f"resolve to a retained trace")
            else:
                print(f"{name} p99 exemplar -> {ex} (retained)")
        # leg 3: the drop-faulted request's trace names the ladder
        if router.reprefills < 1 or not router.reprefill_ids:
            problems.append("the drop injection never exercised the "
                            "re-prefill fallback")
        for rid in sorted(router.reprefill_ids):
            tr = next((r.trace for r in reqs if r.req_id == rid), None)
            names = [] if tr is None else [e.name for e in tr.events]
            if "retry" not in names:
                problems.append(f"faulted request {rid}: trace names no "
                                f"retry rung ({names})")
            if "reprefill" not in names:
                problems.append(f"faulted request {rid}: trace names no "
                                f"re-prefill rung ({names})")
            if tr is not None and "decode" not in tr.tiers():
                problems.append(f"faulted request {rid}: chain never "
                                f"reached the decode tier "
                                f"({tr.tiers()})")
    finally:
        resilience.reset_breaker(serve.HANDOFF_OP)
        rtrace.enable(prev_trace)
        obs.enable(prev_obs)

    for p in problems:
        print(f"TRACE FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"problems": problems}, f, indent=1,
                      sort_keys=True, default=str)
    if problems:
        return 1
    print("trace OK: every request's span chain is gapless with "
          "attributor phases summing exactly to e2e latency; p99 "
          "exemplars resolve to retained traces; the drop-faulted "
          "request names its retry and re-prefill rungs")
    return 0


def _run_profile(args) -> int:
    """The continuous-profiler gate (ISSUE 16; see module docstring):
    (1) an ARMED seeded two-tier replay must rotate windows through the
    real scheduler/router step hooks; (2) every registry family with an
    ``obs.costs`` calculator — the set cross-checked against the
    completeness gate's wiring table — must land a live per-family
    rollup whose attribution agrees with the offline timeline
    reconstructor on the SAME capture; (3) the anomaly selftest must
    pass in BOTH directions (clean replay quiet, seeded regression
    caught with the stall triple and exemplar named)."""
    from triton_distributed_tpu import obs, resilience, serve
    from triton_distributed_tpu.analysis import completeness, registry
    from triton_distributed_tpu.obs import anomaly, continuous, flight
    from triton_distributed_tpu.obs import timeline as tl_mod
    from triton_distributed_tpu.obs.costs import FAMILY_COSTS

    problems: list[str] = []
    prev_obs = obs.enabled()
    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    obs.enable(True)
    flight.enable(True)
    continuous.enable(True)
    flight.clear()
    obs.serve_stats.STATS.reset()
    resilience.reset_breaker(serve.HANDOFF_OP)
    # a fresh unpersisted profiler so the gate never touches disk and
    # never inherits another harness's accumulators
    prev_installed = continuous.install(continuous.ContinuousProfiler(
        window_steps=16, out_dir=""))
    try:
        # leg 1: the armed two-tier replay (the --handoff harness, one
        # home) — the scheduler/router step hooks must rotate windows
        router, _plane, reqs = _two_tier_replay(args.seed, [])
        prof = continuous.profiler()
        snap = prof.snapshot()
        nonterminal = [r for r in reqs if not r.done]
        if nonterminal:
            problems.append(f"replay: {len(nonterminal)} request(s) "
                            f"never terminal under TDT_PROFILE")
        if snap["windows_total"] < 1:
            problems.append(
                f"replay: the step hooks rotated no window over "
                f"{router.prefill.steps} router steps "
                f"(window_steps=16) — the profiler is not wired into "
                f"the serve loop")
        last = prof.last_window()
        if last is not None and last.get("window_steps") != 16:
            problems.append(
                f"replay: window reports window_steps="
                f"{last.get('window_steps')}, profiler configured 16")
        print(f"profile replay: {len(reqs)} requests, "
              f"{router.prefill.steps} prefill steps -> "
              f"{snap['windows_total']} windows rotated")

        # leg 2: per-family rollup coverage + live-vs-offline agreement
        # on the SAME capture, for every family the completeness gate
        # says carries a cost calculator (no silent subset: the family
        # list is the registry's, the calculator set is cross-checked)
        wiring = completeness.check()
        if wiring:
            problems += [f"completeness cross-check: {p}"
                         for p in wiring]
        # the wiring table (GOLDEN) names each family's cost-calculator
        # keys (hierarchical's are the hier_* variants); a family whose
        # named keys are absent from FAMILY_COSTS is a wiring break the
        # completeness leg above already flags
        families = [f for f in registry.FAMILIES
                    if any(k in FAMILY_COSTS for k in
                           completeness.GOLDEN.get(f, {}).get("costs",
                                                              ()))]
        skipped = sorted(set(registry.FAMILIES) - set(families))
        if skipped:
            print(f"(families without a cost calculator, skipped: "
                  f"{skipped})")
        for family in families:
            streams = None
            for n in (2, 4, 8):
                try:
                    _, streams = flight.record_family(family, n)
                    break
                except (IndexError, ValueError):
                    continue
            if streams is None:
                problems.append(f"{family}: no registry case records "
                                f"at ranks 2/4/8")
                continue
            fresh = continuous.ContinuousProfiler(window_steps=1,
                                                  out_dir="")
            flight.clear()
            flight.feed_streams(family, streams)
            fresh.on_step("decode", 1)
            rollups = {k: r for k, r in fresh.lifetime_rollups().items()
                       if k[0] == family}
            if not rollups:
                problems.append(
                    f"{family}: the live drain produced no rollup for "
                    f"the fed capture (keys: "
                    f"{sorted(fresh.lifetime_rollups())})")
                continue
            live = next(iter(rollups.values()))
            off = tl_mod.reconstruct(streams, kernel=family)
            off_exposed = sum(r.exposed_us for r in off.rows)
            off_compute = sum(r.compute_us for r in off.rows)
            pairs = (("exposed_us", live.exposed_us, off_exposed),
                     ("compute_us", live.compute_us, off_compute),
                     ("critical_us", live.critical_us, off.critical_us),
                     ("sol_us", live.sol_us, off.sol_us),
                     ("skew_us", live.skew_us, off.skew_us))
            for name, lv, ov in pairs:
                if abs(lv - ov) > 1e-6 + 1e-9 * abs(ov):
                    problems.append(
                        f"{family}: live rollup {name}={lv!r} disagrees "
                        f"with the offline timeline {ov!r} on the same "
                        f"capture")
        print(f"profile coverage: {len(families)} famil"
              f"{'y' if len(families) == 1 else 'ies'} fed and "
              f"reconciled against the offline reconstructor")

        # leg 3: the anomaly selftest, both directions
        problems += anomaly.selftest(args.seed)
    finally:
        resilience.reset_breaker(serve.HANDOFF_OP)
        continuous.install(prev_installed)
        anomaly.clear()
        flight.clear()
        continuous.enable(prev_prof)
        flight.enable(prev_flight)
        obs.enable(prev_obs)

    for p in problems:
        print(f"PROFILE FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"problems": problems}, f, indent=1,
                      sort_keys=True, default=str)
    if problems:
        return 1
    print("profile OK: armed replay rotated windows through the step "
          "hooks; every cost-calculated registry family lands a live "
          "rollup agreeing with the offline timeline on the same "
          "capture; anomaly selftest passes both directions")
    return 0


def _run_persistent(args) -> int:
    """The persistent-decode gate (ISSUE 13; see module docstring):
    protocol matrix, fault cells with the inter-layer semaphore named,
    the headless dispatch-count assertion, and the scheduler
    window-parity smoke."""
    from triton_distributed_tpu import analysis, resilience, serve

    problems: list[str] = []

    # 1: the chained multi-layer protocol at ranks {2,4,8}
    results = analysis.verify_all(ranks=(2, 4, 8),
                                  kernel_filter="persistent_decode")
    if not results:
        problems.append("no persistent_decode kernel cases registered")
    for case, violations in results:
        status = "OK" if not violations else "VIOLATION"
        print(f"{case.name:<28} ranks={case.n:<2} {status}")
        for v in violations:
            print(f"    [{v.check}] {v.message}")
            problems.append(f"{case.name}: [{v.check}] {v.message}")

    # 2: every fault class against the chain; must-detect classes must
    # name a semaphore of the SHARED re-armed set (the inter-layer edge)
    cells = resilience.run_persistent_cells(seed=args.seed)
    for row in cells:
        named = f"  [{', '.join(row['named'])}]" if row["named"] else ""
        print(f"{row['kernel']:<26} {row['fault']:<16} "
              f"{row['outcome'].upper():<9}{named}")
    problems += resilience.verify_matrix(cells, min_kernels_per_class=1)
    chain_sems = ("ack_sems", "recv_sems", "ag_recv_sems", "send_sems",
                  "ag_send_sem")
    chain_named = [r for r in cells
                   if r["outcome"] == "detected"
                   and any(any(s in n for s in chain_sems)
                           for n in r["named"])]
    if not chain_named:
        problems.append(
            "no fault detection named a semaphore of the shared chain "
            "set — the inter-layer dependency edge is not being "
            "exercised")

    # 3: headless dispatch-count assertion.  The step-bundle harness
    # (embed gather + lax.scan + final norm + lm_head + argmax) must add
    # exactly ONE launch-shaped equation around the step function, and
    # the module must carry exactly ONE pallas_call — together: a
    # persistent step bundle is <= 2 dispatches per token window, the
    # decode_dispatches_per_bundle claim (slice captures measure the
    # real traced number; this pin holds on any jax build).
    import jax
    import jax.numpy as jnp

    from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
    from triton_distributed_tpu.models import ModelConfig, Qwen3
    from triton_distributed_tpu.models.kv_cache import init_paged_cache
    from triton_distributed_tpu.ops import persistent_decode as pdm

    mesh = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    cfg = ModelConfig(num_layers=2, hidden=32, intermediate=64,
                      num_heads=4, num_kv_heads=2, head_dim=8, vocab=64,
                      max_length=32, dtype=jnp.float32)
    model = Qwen3(cfg, mesh, decode_mode="persistent")
    params = model.init(jax.random.key(0), scale=0.05)
    cache = init_paged_cache(mesh, cfg.num_layers, 2, cfg.num_kv_heads,
                             cfg.max_length, cfg.head_dim, cfg.dtype,
                             page_size=8)
    tok = jnp.zeros((2,), jnp.int32)
    orig = pdm.persistent_decode_step
    pdm.persistent_decode_step = \
        lambda x, sp, pk, pv, table, lens, mesh, axis="tp", **kw: (x, pk, pv)
    try:
        harness = pdm.count_bundle_dispatches(model, params, cache, tok, 4)
    finally:
        pdm.persistent_decode_step = orig
    with open(pdm.__file__) as f:
        launches = f.read().count("pl.pallas_call(")
    print(f"bundle harness dispatches={harness} module pallas_calls="
          f"{launches} -> per-bundle bound {harness + launches}")
    if harness != 1:
        problems.append(
            f"step-bundle harness contributes {harness} dispatch-shaped "
            f"equations (want exactly 1, the lm_head GEMM) — the scan "
            f"harness grew a hidden dispatch")
    if launches != 1:
        problems.append(
            f"ops/persistent_decode.py builds {launches} pallas_calls "
            f"(want exactly 1 persistent grid) — the <= 2 per-bundle "
            f"claim no longer follows structurally")

    # 4: scheduler window-parity smoke — steps_per_dispatch 4 vs 1 over
    # a seeded pool-pressured trace: same completions, identical
    # tokens, zero leaks, fewer dispatch windows
    def run(spd):
        backend = serve.SimBackend(slots=4, page_size=4, pool_pages=17,
                                   max_length=64, steps_per_dispatch=spd)
        sched = serve.Scheduler(backend, serve.SchedulerConfig(
            max_queue_depth=64))
        arrivals = serve.synthetic_trace(args.seed + 3, 24,
                                         mean_interarrival_steps=0.5,
                                         prompt_len=(2, 12),
                                         max_new=(4, 12))
        report = serve.replay(sched, arrivals, max_steps=20_000)
        return sched, report

    s1, r1 = run(1)
    s4, r4 = run(4)
    print(f"window smoke: spd=1 {len(r1.completed)} completed / "
          f"{s1.preemptions} preempted / {s1.decode_windows} windows; "
          f"spd=4 {len(r4.completed)} completed / {s4.preemptions} "
          f"preempted / {s4.decode_windows} windows")
    for tag, s, r in (("spd=1", s1, r1), ("spd=4", s4, r4)):
        problems += [f"window smoke {tag}: {p}" for p in r.problems()]
        bad = [q.req_id for q in r.completed
               if q.tokens != s.backend.expected_tokens(q)]
        if bad:
            problems.append(f"window smoke {tag}: token parity broken "
                            f"vs the deterministic golden for {bad}")
    if sorted(tuple(q.tokens) for q in r1.completed) != \
            sorted(tuple(q.tokens) for q in r4.completed):
        problems.append("window smoke: steps_per_dispatch=4 produced "
                        "different token sequences than =1 — windows "
                        "are not membership-transparent")
    if s4.preemptions < 1:
        problems.append("window smoke: the pressured trace never "
                        "preempted — preemption-between-windows is not "
                        "being exercised")
    if s4.decode_windows >= s1.decode_windows:
        problems.append(
            f"window smoke: spd=4 used {s4.decode_windows} dispatch "
            f"windows vs {s1.decode_windows} at spd=1 — batching bought "
            f"nothing")

    for p in problems:
        print(f"PERSISTENT FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"cells": cells, "harness_dispatches": harness,
                       "module_pallas_calls": launches,
                       "problems": problems}, f, indent=1,
                      sort_keys=True, default=str)
    if problems:
        return 1
    print("persistent OK: chained multi-layer protocol clean at ranks "
          "{2,4,8}; fault cells detected-or-survived with the "
          "inter-layer semaphore named; step bundle bounded at 2 "
          "dispatches; window parity pinned with zero leaks")
    return 0


def _run_timeline(args) -> int:
    from triton_distributed_tpu.obs import flight, timeline

    problems = []
    results = []
    for family, n, variant in (("allgather", 2, "ring_1d"),
                               ("ag_gemm", 2, "unidir")):
        name, streams = flight.record_family(family, n, variant=variant)
        tl = timeline.reconstruct(streams, kernel=name)
        results.append(tl)
        print(f"{name:<28} ranks={tl.n:<2} critical={tl.critical_us:.3f}us "
              f"skew={tl.skew_us:.3f}us pct_sol={100 * tl.pct_sol:.1f}% "
              f"waits={len(tl.waits)}")
        problems += [f"{name}: {p}" for p in timeline.check_balanced(tl)]
        if not tl.waits:
            problems.append(f"{name}: no attributed waits reconstructed")
    for p in problems:
        print(f"TIMELINE FAIL: {p}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "cases": [{"kernel": tl.kernel, "ranks": tl.n,
                           "critical_us": tl.critical_us,
                           "pct_sol": tl.pct_sol,
                           "waits": len(tl.waits)} for tl in results],
                "problems": problems,
            }, f, indent=1, sort_keys=True)
    if problems:
        return 1
    print("timeline OK: reconstruction complete, attribution balanced, "
          "every stall named with its (semaphore, chunk, peer)")
    return 0


def _run_history(args) -> int:
    """Delegate to ``scripts/bench_history.py --check`` (one
    implementation of the sentinel; this is just the lint entry)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_history.py")
    spec = importlib.util.spec_from_file_location("_bench_history", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    argv = ["--check"]
    if args.json:
        argv += ["--json", args.json]
    return mod.main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
