#!/usr/bin/env python
"""Overlap-efficiency report from recorded span traces.

The standing instrument for every perf PR (ISSUE 1): given one or more
Chrome-trace span files exported by ``triton_distributed_tpu.obs``
(one per process — e.g. ``obs.tracing.export(f"spans_r{rank}.json")``
after a traced decode), print the per-step table of comm-exposed vs
compute time and the overlap ratio the paper's design is supposed to
maximize.

Usage:
    python scripts/obs_report.py spans_r0.json spans_r1.json
    python scripts/obs_report.py merged_trace.json.gz
    python scripts/obs_report.py --selftest
    python scripts/obs_report.py r0.json r1.json --json report.json
    python scripts/obs_report.py --timeline ag_gemm --ranks 4
    python scripts/obs_report.py --timeline flight_streams.json --chrome t.json
    python scripts/obs_report.py --live http://127.0.0.1:9100
    python scripts/obs_report.py --live --json -          # machine-clean JSON
    python scripts/obs_report.py --diff profile_dir/ live_dir/
    python scripts/obs_report.py --diff r18 r19
    python scripts/obs_report.py --request p99 --trace-file traces.json

Multiple inputs are merged with ``tools.trace_merge`` (rank i = argv
order), so per-rank lanes stay disjoint; a single input may already be a
merged trace.  ``--json`` additionally writes the rows + aggregate as
JSON for machine consumers (CI gates on mean overlap).

``--request <trace_id>`` is the per-request waterfall (ISSUE 14,
docs/observability.md "Request tracing"): given a trace id it prints
the request's gapless span chain (offsets, durations, tiers, tags),
its overlay events (wire/verify splits, retry rungs) and the SLO
attribution footer.  Traces resolve against ``--trace-file`` (a JSON
dump from ``obs.request_trace.export_traces`` or a saved
``/debug/trace/<id>`` payload); without a file the in-process ring is
consulted (useful from a REPL or test).  ``--request list`` prints the
available ids.  ``--request p99`` (or ``p50``) is the cohort view
(ISSUE 20): it selects the p99-exemplar cohort, diffs it against the
p50 cohort span-by-span (``obs.diff.diff_cohorts``) so the answer to
"what do the slow requests spend their extra time on" is one ranked
phase decomposition, then prints the slowest exemplar's waterfall.

``--diff A B`` is the regression-forensics leg (ISSUE 20,
docs/observability.md "Regression forensics"): given any two
comparable captures it prints the ranked causal decomposition of the
delta via ``obs.diff``.  Each operand is sniffed by shape — ``r<N>``
names a committed bench round (``obs.history.load_rounds``), a
directory or ``profile_*.jsonl`` segment is a continuous-profiler
time-series (the LAST rotated window is the capture), and a JSON file
is either a saved window / ``/debug/profile`` snapshot or a trace dump
(``export_traces`` → the whole file is the cohort).  Both operands
must resolve to the same capture kind.  ``--json`` dumps the raw
attribution dict for machine consumers.

``--timeline`` is the flight-recorder view (docs/observability.md
"Flight recorder"): given a kernel family name it records every rank of
the registry case under deterministic record mode, reconstructs the
cross-rank timeline (``obs.timeline``), and prints the per-collective
table — compute / wire / exposed-wait / straggler-skew columns, the
achieved-vs-SOL percentage, and every stall attributed to its
(semaphore, chunk, peer) triple.  Given a path (``obs.flight.
save_streams`` JSON) it reconstructs the saved streams instead.
``--chrome`` additionally writes the timeline as Chrome-trace JSON with
flow arrows linking each stall to the transfer it starved for.

``--fleet`` is the fleet observability operator view (ISSUE 19,
docs/observability.md "Fleet observability"): given a telemetry-plane
URL it fetches ``/debug/fleet`` and renders the federated per-replica
table (merged p99s, per-replica drill-down, imbalance gauges), any
open fleet-scope anomalies, and the control-decision ledger tail; with
no operand it snapshots the in-process plane (``TDT_FLEET_OBS=1``).
Exit code 1 when the latest fleet window carries anomalies.

``--live`` is the continuous profiler's operator view (ISSUE 16,
docs/observability.md "Continuous profiling"): given a telemetry-plane
URL it fetches ``/debug/profile`` and renders the per-(family x
topology x tier) rollup table with the window/anomaly state; with no
operand it snapshots the IN-PROCESS profiler (a REPL or harness that
armed ``TDT_PROFILE=1`` locally).  Exit code 1 when the latest window
carries anomalies, so a cron probe can page on it.  With ``--json``
stdout is machine-clean — the human table and diagnostics move to
stderr, and ``--json -`` writes the JSON payload to stdout (the
``bench_history --json`` discipline), so
``obs_report.py --live URL --json - | jq .`` just works.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="*",
                    help="span trace files (one per rank, or one merged)")
    ap.add_argument("--selftest", action="store_true",
                    help="run on the canned two-rank span set and verify "
                         "the known ratios (plus a 2-rank flight-timeline "
                         "reconstruction check)")
    ap.add_argument("--json", metavar="PATH",
                    help="also write rows + aggregate as JSON")
    ap.add_argument("--timeline", metavar="FAMILY_OR_PATH",
                    help="flight-recorder timeline: a kernel family "
                         "(recorded fresh at --ranks) or a saved "
                         "flight-streams JSON")
    ap.add_argument("--ranks", type=int, default=4,
                    help="rank count for --timeline family recording "
                         "(default 4)")
    ap.add_argument("--variant", default=None,
                    help="registry case variant filter for --timeline "
                         "(e.g. unidir)")
    ap.add_argument("--save", metavar="PATH",
                    help="with --timeline: also save the recorded flight "
                         "streams as JSON")
    ap.add_argument("--chrome", metavar="PATH",
                    help="with --timeline: also write the reconstructed "
                         "timeline as Chrome-trace JSON with stall flow "
                         "arrows")
    ap.add_argument("--request", metavar="TRACE_ID",
                    help="per-request waterfall for one trace id "
                         "('list' prints the available ids; 'p99'/'p50' "
                         "prints the quantile cohort diffed against the "
                         "p50 cohort)")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="regression forensics: ranked causal "
                         "decomposition of the delta between two "
                         "comparable captures (r<N> round ids, profiler "
                         "window files/dirs, or trace dumps)")
    ap.add_argument("--trace-file", metavar="PATH",
                    help="with --request: resolve trace ids from this "
                         "JSON dump (obs.request_trace.export_traces / "
                         "a saved /debug/trace/<id> payload) instead of "
                         "the in-process ring")
    ap.add_argument("--live", nargs="?", const="local", metavar="URL",
                    help="continuous-profiler view: fetch /debug/profile "
                         "from a telemetry-plane URL, or snapshot the "
                         "in-process profiler when no URL is given")
    ap.add_argument("--fleet", nargs="?", const="local", metavar="URL",
                    help="fleet observability view (TDT_FLEET_OBS=1): "
                         "fetch /debug/fleet from a telemetry-plane URL, "
                         "or snapshot the in-process federation plane + "
                         "decision ledger when no URL is given; exit 1 "
                         "on an open fleet-scope anomaly")
    args = ap.parse_args(argv)

    from triton_distributed_tpu.obs import report

    if args.diff:
        return _run_diff(args)
    if args.fleet:
        return _run_fleet_view(args)
    if args.live:
        return _run_live(args)
    if args.request:
        return _run_request(args)
    if args.timeline:
        return _run_timeline(args)
    if args.selftest:
        sys.stdout.write(report.selftest())
        _timeline_selftest()
        print("selftest OK")
        return 0
    if not args.traces:
        ap.error("no trace files given (or use --selftest)")

    if len(args.traces) == 1:
        events = report.load_trace(args.traces[0])
    else:
        from triton_distributed_tpu.tools.trace_merge import merge_traces

        with tempfile.TemporaryDirectory() as td:
            merged = os.path.join(td, "merged.json")
            merge_traces(list(args.traces), list(range(len(args.traces))),
                         merged)
            events = report.load_trace(merged)

    rows = report.overlap_report(events)
    sys.stdout.write(report.format_report(rows))
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"rows": rows, "aggregate": report.aggregate(rows)},
                      f, indent=1, sort_keys=True)
    return 0


def _run_live(args) -> int:
    """The ``--live`` leg: one continuous-profiler snapshot (remote
    ``/debug/profile`` or the in-process profiler), rendered as the
    rollup table.  Exit 1 when the latest window carries anomalies.

    With ``--json``, stdout is machine-clean: the human table and
    diagnostics go to stderr and ``--json -`` writes the payload to
    stdout (the ``bench_history --json`` discipline), so piping into
    ``jq`` never sees a table row."""
    from triton_distributed_tpu.obs import continuous

    # Human output: stdout normally, stderr under --json so a pipe
    # consumer gets ONLY the JSON document.
    human = sys.stderr if args.json else sys.stdout

    if args.live == "local":
        prof = continuous.profiler() if continuous.enabled() else None
        snap = prof.snapshot() if prof is not None \
            else {"enabled": continuous.enabled()}
        where = "in-process profiler"
    else:
        import urllib.request

        url = args.live.rstrip("/") + "/debug/profile"
        with urllib.request.urlopen(url, timeout=10) as r:
            snap = json.load(r)
        where = url
    human.write(continuous.format_snapshot(snap))
    if not snap.get("enabled"):
        print(f"profiler not armed at {where} "
              f"(set TDT_PROFILE=1; docs/observability.md)", file=human)
        if not args.json:
            return 0
    if args.json:
        if args.json == "-":
            json.dump(snap, sys.stdout, indent=1, sort_keys=True,
                      default=str)
            sys.stdout.write("\n")
        else:
            with open(args.json, "w") as f:
                json.dump(snap, f, indent=1, sort_keys=True, default=str)
        if not snap.get("enabled"):
            return 0
    last = snap.get("last_window") or {}
    return 1 if last.get("anomalies") else 0


def _run_fleet_view(args) -> int:
    """The ``--fleet`` leg (ISSUE 19): one fleet-observability snapshot
    — the federation plane's merged/per-replica view, the last window's
    imbalance gauges, retained fleet anomalies, and the decision-ledger
    tail — from ``/debug/fleet`` (URL) or the in-process plane.  Exit 1
    when the latest fleet window carries anomalies, the ``--live``
    cron-probe contract one level up."""
    from triton_distributed_tpu.obs import decisions, fleet_stats

    if args.fleet == "local":
        snap = {"fleet_stats": fleet_stats.snapshot_dump(),
                "decisions": decisions.tail_dump(64)}
        where = "in-process fleet plane"
    else:
        import urllib.request

        url = args.fleet.rstrip("/") + "/debug/fleet"
        with urllib.request.urlopen(url, timeout=10) as r:
            snap = json.load(r)
        where = url
    fs = snap.get("fleet_stats") or {}
    led = snap.get("decisions") or {}
    if not fs.get("replicas"):
        print(f"fleet plane not armed at {where} "
              f"(set TDT_FLEET_OBS=1; docs/observability.md)")
        return 0
    print(f"fleet: {len(fs['replicas'])} replica(s), "
          f"{fs.get('windows', 0)} window(s) of "
          f"{fs.get('window_steps', '?')} steps, "
          f"{fs.get('anomalies_total', 0)} anomalies total")
    merged = fs.get("merged") or {}
    for name in ("ttft_ms", "request_ms"):
        sk = merged.get(name) or {}
        qs = sk.get("quantiles") or {}
        if sk.get("count"):
            print(f"  fleet {name}: p50={qs.get('p50', 0):.1f} "
                  f"p99={qs.get('p99', 0):.1f} (n={sk['count']})")
    print(f"  tokens/s (window): "
          f"{merged.get('tokens_per_s_window', 0.0):.2f}")
    print(f"{'replica':<10} {'role':<8} {'ttft p99':>10} "
          f"{'req p99':>10} {'tok/s':>8} {'requests':>9} {'sheds':>6}")
    for rid, row in sorted((fs.get("replicas") or {}).items()):
        print(f"{rid:<10} {row.get('role') or '?':<8} "
              f"{row.get('ttft_ms_p99', 0.0):>10.1f} "
              f"{row.get('request_ms_p99', 0.0):>10.1f} "
              f"{row.get('tokens_per_s_window', 0.0):>8.2f} "
              f"{row.get('requests_total', 0):>9.0f} "
              f"{row.get('sheds_total', 0):>6.0f}")
    totals = fs.get("last_window_totals") or {}
    if totals:
        print("last window: " + "  ".join(
            f"{k.removeprefix('fleet_')}={v:.3g}"
            for k, v in sorted(totals.items())
            if isinstance(v, (int, float))))
    anomalies = fs.get("anomalies") or []
    for a in anomalies:
        print(f"FLEET ANOMALY: {a.get('summary', a)}")
    tail = led.get("tail") or []
    if tail:
        print(f"decision ledger ({led.get('total', 0)} total; "
              f"last {len(tail)}):")
        sys.stdout.write(decisions.format_tail(tail, limit=len(tail)))
    elif led.get("enabled"):
        print("decision ledger: empty")
    else:
        print("decision ledger not armed (TDT_FLEET_OBS=1)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True, default=str)
    return 1 if anomalies else 0


def _load_capture(spec: str):
    """Sniff one ``--diff`` operand into ``(kind, label, payload)``.

    ``kind`` is the pairing axis (``round`` / ``window`` / ``cohort``)
    — both operands must land on the same one.  ``r<N>`` (or a bare
    integer) is a committed bench round; a directory or a
    ``profile_*.jsonl`` segment is a continuous-profiler time-series
    whose LAST rotated window is the capture; a JSON file is a saved
    window dict, a ``/debug/profile`` snapshot (its ``last_window``),
    or a trace dump (the whole file becomes the cohort)."""
    import re

    from triton_distributed_tpu.obs import history, request_trace

    m = re.fullmatch(r"r?(\d+)", spec)
    if m and not os.path.exists(spec):
        want = int(m.group(1))
        rounds = {r.round: r for r in history.load_rounds(".")}
        if want not in rounds:
            raise SystemExit(
                f"--diff: round {spec!r} not committed "
                f"(have {sorted(rounds)})")
        return "round", f"r{want}", rounds[want]
    if os.path.isdir(spec):
        windows = history.load_profile_windows(spec)
        if not windows:
            raise SystemExit(f"--diff: no profile_*.jsonl windows "
                             f"under {spec!r}")
        return "window", f"{spec} (window {len(windows)})", windows[-1]
    if not os.path.exists(spec):
        raise SystemExit(f"--diff: {spec!r} is neither a committed "
                         f"round id nor a file")
    if spec.endswith(".jsonl"):
        windows = [json.loads(ln) for ln in open(spec)
                   if ln.strip()]
        if not windows:
            raise SystemExit(f"--diff: {spec!r} holds no windows")
        return "window", f"{spec} (window {len(windows)})", windows[-1]
    with open(spec) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "last_window" in doc:   # /debug/profile
        if not doc["last_window"]:
            raise SystemExit(f"--diff: snapshot {spec!r} has no "
                             f"rotated window yet")
        return "window", f"{spec} (last_window)", doc["last_window"]
    if isinstance(doc, dict) and "rollups" in doc:       # one window
        return "window", spec, doc
    traces = request_trace.load_traces(spec)             # trace dump
    if not traces:
        raise SystemExit(f"--diff: {spec!r} is not a recognised "
                         f"capture (no rounds/windows/traces)")
    return "cohort", f"{spec} ({len(traces)} traces)", traces


def _run_diff(args) -> int:
    """The ``--diff A B`` leg (ISSUE 20): resolve both operands to the
    same capture kind and print the ranked causal decomposition of the
    delta (``obs.diff``).  A is the reference, B the suspect — positive
    deltas are regressions in B."""
    from triton_distributed_tpu.obs import diff

    kind_a, label_a, a = _load_capture(args.diff[0])
    kind_b, label_b, b = _load_capture(args.diff[1])
    if kind_a != kind_b:
        print(f"--diff: captures are not comparable — "
              f"{args.diff[0]!r} is a {kind_a}, "
              f"{args.diff[1]!r} is a {kind_b}")
        return 2
    if kind_a == "round":
        d = diff.diff_rounds(a, b)
    elif kind_a == "window":
        d = diff.diff_windows(a, b)
    else:
        d = diff.diff_cohorts(a, b, label_a=label_a, label_b=label_b)
    sys.stdout.write(diff.format_diff(d))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(d, f, indent=1, sort_keys=True, default=str)
    return 0


def _run_request(args) -> int:
    """The ``--request`` leg: resolve one trace (file dump or the
    in-process ring) and print its waterfall + attribution."""
    from triton_distributed_tpu.obs import request_trace

    if args.trace_file:
        traces = {t.trace_id: t
                  for t in request_trace.load_traces(args.trace_file)}
        where = args.trace_file
    else:
        traces = {t.trace_id: t
                  for t in request_trace.RING.recent(
                      len(request_trace.RING))}
        where = "the in-process ring"
    if args.request == "list":
        for tid in traces:
            print(tid)
        print(f"{len(traces)} trace(s) in {where}")
        return 0
    if args.request in ("p50", "p99"):
        return _run_request_cohort(args, list(traces.values()), where)
    tr = traces.get(args.request)
    if tr is None:
        print(f"trace {args.request!r} not found in {where} "
              f"({len(traces)} trace(s): {list(traces)[-8:]})")
        return 1
    sys.stdout.write(request_trace.format_waterfall(tr))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(tr.to_dict(), f, indent=1, sort_keys=True)
    return 0


def _run_request_cohort(args, traces, where: str) -> int:
    """``--request p99`` / ``--request p50``: the quantile-cohort view
    (ISSUE 20).  Select the requested quantile's cohort, diff it against
    the p50 cohort span-by-span so the extra time the slow requests
    spend is a RANKED per-phase decomposition (``obs.diff``), then print
    the slowest exemplar's waterfall for drill-down."""
    from triton_distributed_tpu.obs import diff, request_trace

    q = 0.99 if args.request == "p99" else 0.5
    # p99 exemplars are by definition few — a narrow width keeps the
    # cohort the actual tail rather than the upper half.
    cohort = request_trace.select_cohort(
        traces, q, width=0.02 if q >= 0.9 else 0.2)
    if not cohort:
        print(f"no closed traces in {where} "
              f"(arm TDT_TRACE=1; docs/observability.md)")
        return 1
    base = request_trace.select_cohort(traces, 0.5)
    d = diff.diff_cohorts(base, cohort,
                          label_a=f"p50 cohort (n={len(base)})",
                          label_b=f"{args.request} cohort "
                                  f"(n={len(cohort)})")
    sys.stdout.write(diff.format_diff(d))
    exemplar = max(cohort, key=lambda t: t.total_ms)
    print(f"\nslowest exemplar {exemplar.trace_id}:")
    sys.stdout.write(request_trace.format_waterfall(exemplar))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(d, f, indent=1, sort_keys=True, default=str)
    return 0


def _run_timeline(args) -> int:
    from triton_distributed_tpu.obs import flight, timeline

    if os.path.exists(args.timeline):
        name, streams = flight.load_streams(args.timeline)
    else:
        name, streams = flight.record_family(
            args.timeline, args.ranks, variant=args.variant)
    if args.save:
        flight.save_streams(name, streams, args.save)
    tl = timeline.reconstruct(streams, kernel=name)
    sys.stdout.write(timeline.format_table(tl))
    if args.chrome:
        with open(args.chrome, "w") as f:
            f.write('{"displayTimeUnit":"ms","traceEvents":')
            f.write(json.dumps(timeline.to_chrome(tl),
                               separators=(",", ":")))
            f.write("}")
        print(f"chrome trace (with stall flow arrows): {args.chrome}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "kernel": tl.kernel, "ranks": tl.n,
                "critical_us": tl.critical_us, "skew_us": tl.skew_us,
                "sol_us": tl.sol_us, "pct_sol": tl.pct_sol,
                "stalled": tl.stalled, "pending": list(tl.pending),
                "rows": [vars(r) for r in tl.rows],
                "waits": [dataclasses.asdict(w) for w in tl.waits],
            }, f, indent=1, sort_keys=True)
    return 1 if tl.stalled else 0


def _timeline_selftest() -> None:
    """Record a 2-rank AllGather, reconstruct, and assert the
    reconstruction is complete, symmetric, and fully attributed — the
    flight-timeline half of ``--selftest``."""
    from triton_distributed_tpu.obs import flight, timeline

    name, streams = flight.record_family("allgather", 2, variant="ring_1d")
    tl = timeline.reconstruct(streams, kernel=name)
    problems = timeline.check_balanced(tl)
    if problems:
        raise AssertionError(
            f"timeline selftest: {name} reconstruction unbalanced: "
            f"{problems}")
    if not tl.waits or tl.critical_us <= 0:
        raise AssertionError(
            f"timeline selftest: {name} reconstructed no attributed "
            f"waits / zero critical path")
    print(f"timeline selftest: {name} ranks={tl.n} "
          f"critical={tl.critical_us:.3f}us pct_sol={100 * tl.pct_sol:.1f}% "
          f"waits attributed={len(tl.waits)}")


if __name__ == "__main__":
    raise SystemExit(main())
