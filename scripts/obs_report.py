#!/usr/bin/env python
"""Overlap-efficiency report from recorded span traces.

The standing instrument for every perf PR (ISSUE 1): given one or more
Chrome-trace span files exported by ``triton_distributed_tpu.obs``
(one per process — e.g. ``obs.tracing.export(f"spans_r{rank}.json")``
after a traced decode), print the per-step table of comm-exposed vs
compute time and the overlap ratio the paper's design is supposed to
maximize.

Usage:
    python scripts/obs_report.py spans_r0.json spans_r1.json
    python scripts/obs_report.py merged_trace.json.gz
    python scripts/obs_report.py --selftest
    python scripts/obs_report.py r0.json r1.json --json report.json

Multiple inputs are merged with ``tools.trace_merge`` (rank i = argv
order), so per-rank lanes stay disjoint; a single input may already be a
merged trace.  ``--json`` additionally writes the rows + aggregate as
JSON for machine consumers (CI gates on mean overlap).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="*",
                    help="span trace files (one per rank, or one merged)")
    ap.add_argument("--selftest", action="store_true",
                    help="run on the canned two-rank span set and verify "
                         "the known ratios")
    ap.add_argument("--json", metavar="PATH",
                    help="also write rows + aggregate as JSON")
    args = ap.parse_args(argv)

    from triton_distributed_tpu.obs import report

    if args.selftest:
        sys.stdout.write(report.selftest())
        print("selftest OK")
        return 0
    if not args.traces:
        ap.error("no trace files given (or use --selftest)")

    if len(args.traces) == 1:
        events = report.load_trace(args.traces[0])
    else:
        from triton_distributed_tpu.tools.trace_merge import merge_traces

        with tempfile.TemporaryDirectory() as td:
            merged = os.path.join(td, "merged.json")
            merge_traces(list(args.traces), list(range(len(args.traces))),
                         merged)
            events = report.load_trace(merged)

    rows = report.overlap_report(events)
    sys.stdout.write(report.format_report(rows))
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"rows": rows, "aggregate": report.aggregate(rows)},
                      f, indent=1, sort_keys=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
