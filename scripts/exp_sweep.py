#!/usr/bin/env python
"""Round-4 measurement harness: careful interleaved sweeps on the real chip.

Usage: python scripts/exp_sweep.py <mode> [rounds]
Modes: gemm7168 gemm4096 gemm8192 group decode attn

Prints per-candidate median seconds/iter and the median per-round ratio
vs the XLA baseline (ratio > 1.0 = candidate faster than XLA).
"""
from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp


def median(xs):
    xs = sorted(x for x in xs if x == x and x > 0)
    return xs[len(xs) // 2] if xs else float("nan")


def run_sweep(engines: dict, iters: int, rounds: int, baseline: str):
    from triton_distributed_tpu.core.utils import (
        interleaved_slope_samples, sync,
    )

    for name, fn in engines.items():
        sync(fn())
        print(f"  compiled {name}", flush=True)
    raw = interleaved_slope_samples(engines, iters, rounds,
                                    target_window_s=0.15)
    times = {n: [dt if dt > 0 else float("nan") for dt in xs][1:]
             for n, xs in raw.items()}
    base = times[baseline]
    print(f"\n{'name':<24} {'med s/iter':>12} {'ratio vs ' + baseline:>16}")
    out = {}
    for name in engines:
        ratios = [b / a for a, b in zip(times[name], base) if a > 0 and b > 0]
        r = median(ratios)
        out[name] = (median(times[name]), r)
        print(f"{name:<24} {median(times[name]):>12.6f} {r:>16.4f}",
              flush=True)
    return out


def gemm(m, n, k, rounds):
    from triton_distributed_tpu.ops.matmul import matmul
    from triton_distributed_tpu.tune.autotuner import matmul_tile_candidates

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), dtype=jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (k, n),
                          dtype=jnp.bfloat16)
    xla = jax.jit(lambda a, b: jnp.matmul(a, b))
    engines = {"xla": lambda: xla(a, b)}
    for bm, bn, bk in matmul_tile_candidates(m, n, k):
        if bm * bn * 4 > 8 * 2**20:  # skip huge-acc configs that can't win
            continue
        name = f"p{bm}x{bn}x{bk}"
        engines[name] = (lambda bm=bm, bn=bn, bk=bk:
                         matmul(a, b, bm=bm, bn=bn, bk=bk))
    run_sweep(engines, 32, rounds, "xla")


def group(rounds):
    from triton_distributed_tpu.ops.group_gemm import (
        GroupGemmConfig, grouped_matmul,
    )
    from triton_distributed_tpu.tune.autotuner import matmul_tile_candidates

    t, k, n, e = 8192, 7168, 2048, 8
    kx, kw = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (t, k), jnp.bfloat16)
    w = jax.random.normal(kw, (e, k, n), jnp.bfloat16)
    splits = jnp.asarray([2048, 512, 1536, 0, 1024, 1408, 640, 1024],
                         jnp.int32)
    from triton_distributed_tpu.core.utils import sync

    ragged = jax.jit(lambda x, w, s: jax.lax.ragged_dot(x, w, s))
    engines = {"xla": lambda: ragged(x, w, splits)}
    cands = [(256, 2048, 512)] + matmul_tile_candidates(t, n, k)
    for bm, bn, bk in cands:
        name = f"p{bm}x{bn}x{bk}"
        g = jax.jit(functools.partial(
            grouped_matmul, config=GroupGemmConfig(bm, bn, bk)))
        f = (lambda g=g: g(x, w, splits))
        try:
            sync(f())
            engines[name] = f
        except Exception as e:
            print(f"skip {name}: {str(e)[:70]}")
    run_sweep(engines, 16, rounds, "xla")


def decode(rounds):
    from triton_distributed_tpu.ops.attention import (
        decode_attention_state, merge_decode_states, safe_normalize_decode,
    )

    b, h, hk, s, d = 8, 32, 8, 8192, 128
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.bfloat16)

    @jax.jit
    def xla_decode(q, k, v):
        qh = q.reshape(b, hk, h // hk, d).astype(jnp.float32)
        sc = jnp.einsum("bkgd,bksd->bkgs", qh, k.astype(jnp.float32))
        p = jax.nn.softmax(sc * (d ** -0.5), -1)
        out = jnp.einsum("bkgs,bksd->bkgd", p, v.astype(jnp.float32))
        return out.reshape(b, h, d).astype(q.dtype)

    def ours(n_split, bk):
        def f(q, k, v):
            num, m, l = decode_attention_state(
                q, k, v, s, n_split=n_split, block_k=bk)
            num, _, l = merge_decode_states(num, m, l)
            return safe_normalize_decode(
                num[..., 0, :], l[..., 0][..., None], q.dtype)
        return jax.jit(f)

    engines = {"xla": lambda: xla_decode(q, k, v)}
    for ns in (1, 2, 4, 8, 16):
        for bk in (256, 512, 1024, 2048):
            if s % ns or (s // ns) % bk:
                continue
            f = ours(ns, bk)
            engines[f"ns{ns}_bk{bk}"] = (lambda f=f: f(q, k, v))
    run_sweep(engines, 48, rounds, "xla")


def attn(rounds):
    from triton_distributed_tpu.ops.attention import flash_attention

    b, h, s, d = 1, 32, 4096, 128
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
    engines = {}
    for bq in (256, 512, 1024, 2048):
        for bk in (512, 1024, 2048, 4096):
            engines[f"bq{bq}_bk{bk}"] = (
                lambda bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk))
    # report TFLOP/s too
    out = run_sweep(engines, 32, rounds, f"bq512_bk1024")
    flops = 4.0 * b * h * s * s * d / 2
    for name, (t, r) in out.items():
        print(f"{name:<24} {flops / t / 1e12:8.2f} TFLOP/s")


def main():
    mode = sys.argv[1]
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 13
    print(f"devices: {jax.devices()}", flush=True)
    if mode == "gemm7168":
        gemm(7168, 7168, 7168, rounds)
    elif mode == "gemm4096":
        gemm(4096, 4096, 4096, rounds)
    elif mode == "gemm8192":
        gemm(8192, 2048, 7168, rounds)
    elif mode == "group":
        group(rounds)
    elif mode == "decode":
        decode(rounds)
    elif mode == "attn":
        attn(rounds)
    else:
        raise SystemExit(f"unknown mode {mode}")


if __name__ == "__main__":
    main()
