#!/usr/bin/env python
"""Round-over-round bench trajectory report and trend sentinel.

Reads every committed bench round (``BENCH_rNN.json`` driver envelopes
plus ``BENCH_LOCAL_rNN.jsonl`` complete streams) through
``triton_distributed_tpu.obs.history`` and prints the per-metric
trajectory table: draws across rounds, the prior rounds' healthy band,
and WARN annotations for

- an N=3-round monotonic decline in the worse direction (> 5% total),
- a newest draw outside the prior rounds' healthy band even when it is
  above its claims-registry floor (a dip whose symmetric ``retry_value``
  is back inside the band reports as transient).

Every WARN line carries its round-over-round attribution (ISSUE 20,
``obs.diff.rounds_attribution`` via ``history.analyze``): the
co-regressed metrics between the same two rounds, ranked by
worse-direction drift — so the table answers "what ELSE moved when this
regressed" without a separate forensics pass.  ``obs_report.py --diff
rA rB`` is the full two-round decomposition.

Usage:
    python scripts/bench_history.py [root]            # trajectory table
    python scripts/bench_history.py --markdown        # docs-pasteable
    python scripts/bench_history.py --json report.json  ('-' = stdout)
    python scripts/bench_history.py --metric flash    # substring filter
    python scripts/bench_history.py --check           # CI mode
    python scripts/bench_history.py --check --strict  # WARN -> exit 1

``--check`` is the loud half (wired into ``scripts/tdt_lint.py
--history`` and the tier-1 smoke test): exit 1 when a committed round is
**internally inconsistent** — a local stream disagreeing with its
same-round envelope on a shared value, a local record missing a metric
its own sentinel lists as emitted, a crashed sweep (rc != 0 or
sentinel=0), or a record with no parseable metric lines.  Trend findings
stay warnings (the chip's round noise is real) unless ``--strict``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="repo root holding the BENCH_r* records")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full report as JSON ('-' = stdout)")
    ap.add_argument("--markdown", action="store_true",
                    help="emit the trajectory table as markdown")
    ap.add_argument("--metric", default=None,
                    help="only metrics whose name contains this")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: exit 1 on internal inconsistency "
                         "(trend findings warn)")
    ap.add_argument("--strict", action="store_true",
                    help="with --check: trend warnings also fail")
    ap.add_argument("--decline-rounds", type=int, default=None,
                    help="consecutive worse rounds that flag a decline "
                         "(default 3)")
    args = ap.parse_args(argv)

    from triton_distributed_tpu.obs import history

    rounds = history.load_rounds(args.root)
    if not rounds:
        machine = bool(args.json or args.markdown)
        print(f"{args.root}: no BENCH_r*.json / BENCH_LOCAL_r*.jsonl "
              f"records found",
              file=sys.stderr if machine else sys.stdout)
        if args.json:
            # stdout/target stays machine-readable: an empty report
            payload = json.dumps(history.to_json({}, []), indent=1,
                                 sort_keys=True)
            if args.json == "-":
                print(payload)
            else:
                with open(args.json, "w") as f:
                    f.write(payload + "\n")
        return 1 if args.check else 0
    kw = {}
    if args.decline_rounds is not None:
        kw["decline_rounds"] = args.decline_rounds
    trs = history.analyze(rounds, **kw)
    if args.metric:
        trs = {k: v for k, v in trs.items() if args.metric in k}
    problems = history.consistency_problems(rounds)
    warnings = history.all_warnings(trs)

    if args.json:
        payload = json.dumps(history.to_json(trs, problems), indent=1,
                             sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    elif args.markdown:
        sys.stdout.write(history.format_markdown(trs))
    else:
        print(f"{len(rounds)} committed round(s): "
              f"{', '.join(f'r{r.round:02d}({r.source})' for r in rounds)}")
        sys.stdout.write(history.format_table(trs))

    # machine-readable modes keep stdout clean (the JSON payload already
    # embeds "problems"/"warnings"); diagnostics go to stderr there
    diag = sys.stderr if (args.json or args.markdown) else sys.stdout
    for p in problems:
        print(f"PROBLEM {p}", file=diag)
    if args.json or args.markdown:
        for w in warnings:
            print(f"WARN {w}", file=sys.stderr)

    if args.check:
        if problems:
            print(f"bench history check: {len(problems)} internal "
                  f"inconsistency problem(s)", file=diag)
            return 1
        if args.strict and warnings:
            print(f"bench history check (--strict): {len(warnings)} "
                  f"trend warning(s)", file=diag)
            return 1
        print(f"bench history check OK: {len(rounds)} rounds consistent, "
              f"{len(warnings)} trend warning(s)", file=diag)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
