// Multi-rank chrome-trace merge (native).
//
// Reference: python/triton_dist/utils.py:414-584 — process_trace_json
// (":365", remap pid/tid by rank), _merge_json_v2 (":465", concatenate
// per-rank traceEvents), ParallelJsonDumper (":414", a multiprocessing
// pool to make Python JSON IO bearable).  That last class is the tell:
// merging hundreds of MB of trace JSON is exactly the workload CPython
// cannot do fast, so this framework's runtime does it natively — a single
// pass per file, no JSON DOM, gzip via zlib.
//
// Merge semantics (chrome trace format): each input file holds
// {"traceEvents": [...]}; the merged file concatenates all events with
// every event's "pid" offset by rank*1000000 so per-rank process lanes
// stay disjoint in the viewer (the reference's remap uses the same idea).
//
// C ABI (consumed via ctypes from tools/trace_merge.py):
//   int tdt_merge_traces(const char** inputs, const int* ranks, int n,
//                        const char* out_path, int gzip_out);
// returns 0 on success, negative error codes otherwise.

#include <zlib.h>

#include <cctype>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Read a whole file into a string; returns false on IO failure.
bool read_file(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {
    std::fclose(f);
    return false;
  }
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, static_cast<size_t>(size), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(size);
}

// Slice out the contents of the top-level "traceEvents" array
// (between its matching '[' ']'), honoring strings/escapes.
bool trace_events_span(const std::string& s, size_t* begin, size_t* end) {
  size_t key = s.find("\"traceEvents\"");
  if (key == std::string::npos) return false;
  size_t open = s.find('[', key);
  if (open == std::string::npos) return false;
  int depth = 0;
  bool in_str = false;
  for (size_t i = open; i < s.size(); ++i) {
    char c = s[i];
    if (in_str) {
      if (c == '\\') {
        ++i;
      } else if (c == '"') {
        in_str = false;
      }
      continue;
    }
    if (c == '"') {
      in_str = true;
    } else if (c == '[') {
      ++depth;
    } else if (c == ']') {
      if (--depth == 0) {
        *begin = open + 1;
        *end = i;  // exclusive
        return true;
      }
    }
  }
  return false;
}

// Append `events` (the contents of a traceEvents array) to `out`,
// rewriting each event's TOP-LEVEL integer "pid" by +offset.  One pass,
// string-aware, object-depth-aware: "pid" keys nested inside "args" (or
// deeper) pass through untouched, matching the Python fallback's
// isinstance(ev["pid"], int) top-level-only semantics; float pids also
// pass through (the fallback only remaps ints).
void append_remapped(const std::string& ev, long long offset,
                     std::string* out) {
  size_t i = 0;
  bool in_str = false;
  int obj_depth = 0;  // 1 == inside one event object
  while (i < ev.size()) {
    char c = ev[i];
    if (in_str) {
      out->push_back(c);
      if (c == '\\' && i + 1 < ev.size()) {
        out->push_back(ev[i + 1]);
        i += 2;
        continue;
      }
      if (c == '"') in_str = false;
      ++i;
      continue;
    }
    if (c == '{') {
      ++obj_depth;
    } else if (c == '}') {
      --obj_depth;
    } else if (c == '"') {
      if (obj_depth == 1 && ev.compare(i, 5, "\"pid\"") == 0) {
        size_t j = i + 5;
        while (j < ev.size() && std::isspace(static_cast<unsigned char>(ev[j])))
          ++j;
        if (j < ev.size() && ev[j] == ':') {
          ++j;
          while (j < ev.size() &&
                 std::isspace(static_cast<unsigned char>(ev[j])))
            ++j;
          size_t num_start = j;
          if (j < ev.size() && (ev[j] == '-' || std::isdigit(
                  static_cast<unsigned char>(ev[j])))) {
            if (ev[j] == '-') ++j;
            while (j < ev.size() &&
                   std::isdigit(static_cast<unsigned char>(ev[j])))
              ++j;
            bool is_int = j >= ev.size() ||
                          (ev[j] != '.' && ev[j] != 'e' && ev[j] != 'E');
            if (is_int) {
              long long v =
                  std::strtoll(ev.c_str() + num_start, nullptr, 10);
              out->append(ev, i, num_start - i);
              out->append(std::to_string(v + offset));
              i = j;
              continue;
            }
          }
        }
      }
      in_str = true;
      out->push_back(c);
      ++i;
      continue;
    }
    out->push_back(c);
    ++i;
  }
}

bool write_out(const std::string& data, const char* path, int gzip_out) {
  if (gzip_out) {
    gzFile g = gzopen(path, "wb6");
    if (!g) return false;
    bool ok = gzwrite(g, data.data(), static_cast<unsigned>(data.size())) ==
              static_cast<int>(data.size());
    gzclose(g);
    return ok;
  }
  FILE* f = std::fopen(path, "wb");
  if (!f) return false;
  bool ok = std::fwrite(data.data(), 1, data.size(), f) == data.size();
  std::fclose(f);
  return ok;
}

}  // namespace

extern "C" int tdt_merge_traces(const char** inputs, const int* ranks,
                                int n, const char* out_path, int gzip_out) {
  if (n <= 0 || !inputs || !ranks || !out_path) return -1;
  // the merged file keeps the FIRST input's envelope (displayTimeUnit,
  // metadata, stackFrames, ...) with its traceEvents contents replaced by
  // the concatenation of every input's remapped events — same policy as
  // the Python fallback
  std::string first_buf;
  if (!read_file(inputs[0], &first_buf)) return -2;
  size_t env_b = 0, env_e = 0;
  if (!trace_events_span(first_buf, &env_b, &env_e)) return -3;

  std::string events;
  bool first = true;
  std::string buf;
  for (int k = 0; k < n; ++k) {
    buf.clear();
    if (!read_file(inputs[k], &buf)) return -2 - k * 10;
    size_t b = 0, e = 0;
    if (!trace_events_span(buf, &b, &e)) return -3 - k * 10;
    // skip pure-whitespace event arrays
    bool empty = true;
    for (size_t i = b; i < e; ++i)
      if (!std::isspace(static_cast<unsigned char>(buf[i]))) {
        empty = false;
        break;
      }
    if (empty) continue;
    if (!first) events.push_back(',');
    first = false;
    append_remapped(buf.substr(b, e - b),
                    static_cast<long long>(ranks[k]) * 1000000LL, &events);
  }
  std::string merged;
  merged.reserve(first_buf.size() + events.size());
  merged.append(first_buf, 0, env_b);
  merged += events;
  merged.append(first_buf, env_e, std::string::npos);
  return write_out(merged, out_path, gzip_out) ? 0 : -4;
}
