// Native safetensors reader: mmap the file, parse the header, hand out
// zero-copy tensor views.
//
// The native half of the framework's weight-ingest path
// (models/safetensors_io.py) — the TPU-side analogue of the reference
// keeping its hot host paths in native code (csrc/, shmem/ runtimes).
// Reads the safetensors container format: 8-byte little-endian header
// length, a flat JSON header {"name": {"dtype": "...", "shape": [...],
// "data_offsets": [begin, end]}, ...}, then the raw byte buffer.  The
// JSON subset needed is tiny, so the parser is self-contained — no
// dependencies beyond libc.
//
// C ABI (consumed via ctypes):
//   StFile* st_open(const char* path);        NULL on error
//   const char* st_last_error(void);          message for the last failure
//   long st_num_tensors(StFile*);
//   const char* st_name(StFile*, long i);
//   const char* st_dtype(StFile*, long i);    safetensors dtype tag (e.g. "BF16")
//   long st_ndim(StFile*, long i);
//   void st_shape(StFile*, long i, long long* out);
//   const void* st_data(StFile*, long i);     pointer into the mapping
//   long long st_nbytes(StFile*, long i);
//   void st_close(StFile*);

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct Tensor {
  std::string name;
  std::string dtype;
  std::vector<long long> shape;
  uint64_t begin = 0;  // relative to the byte buffer
  uint64_t end = 0;
};

struct Parser {
  const char* p;
  const char* lim;
  bool fail = false;
  std::string err;

  void set_err(const std::string& m) {
    if (!fail) {
      fail = true;
      err = m;
    }
  }
  void ws() {
    while (p < lim && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool eat(char c) {
    ws();
    if (p < lim && *p == c) {
      ++p;
      return true;
    }
    set_err(std::string("expected '") + c + "'");
    return false;
  }
  bool peek(char c) {
    ws();
    return p < lim && *p == c;
  }

  std::string parse_string() {
    if (!eat('"')) return "";
    std::string out;
    while (p < lim && *p != '"') {
      char c = *p++;
      if (c != '\\') {
        out += c;
        continue;
      }
      if (p >= lim) break;
      char e = *p++;
      switch (e) {
        case '"': out += '"'; break;
        case '\\': out += '\\'; break;
        case '/': out += '/'; break;
        case 'b': out += '\b'; break;
        case 'f': out += '\f'; break;
        case 'n': out += '\n'; break;
        case 'r': out += '\r'; break;
        case 't': out += '\t'; break;
        case 'u': {
          if (lim - p < 4) {
            set_err("truncated \\u escape");
            return out;
          }
          unsigned v = 0;
          for (int i = 0; i < 4; ++i) {
            char h = *p++;
            v <<= 4;
            if (h >= '0' && h <= '9') v |= h - '0';
            else if (h >= 'a' && h <= 'f') v |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') v |= h - 'A' + 10;
            else {
              set_err("bad \\u escape");
              return out;
            }
          }
          // encode as UTF-8 (surrogate pairs unsupported: tensor names
          // outside the BMP fail loudly rather than silently mis-read)
          if (v >= 0xD800 && v <= 0xDFFF) {
            set_err("surrogate pairs in names are not supported");
            return out;
          }
          if (v < 0x80) out += static_cast<char>(v);
          else if (v < 0x800) {
            out += static_cast<char>(0xC0 | (v >> 6));
            out += static_cast<char>(0x80 | (v & 0x3F));
          } else {
            out += static_cast<char>(0xE0 | (v >> 12));
            out += static_cast<char>(0x80 | ((v >> 6) & 0x3F));
            out += static_cast<char>(0x80 | (v & 0x3F));
          }
          break;
        }
        default:
          set_err("bad escape");
          return out;
      }
    }
    if (p >= lim) {
      set_err("unterminated string");
      return out;
    }
    ++p;  // closing quote
    return out;
  }

  long long parse_int() {
    ws();
    bool neg = false;
    if (p < lim && *p == '-') {
      neg = true;
      ++p;
    }
    if (p >= lim || *p < '0' || *p > '9') {
      set_err("expected integer");
      return 0;
    }
    unsigned long long v = 0;
    while (p < lim && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
    return neg ? -static_cast<long long>(v) : static_cast<long long>(v);
  }

  // skip any JSON value (used for __metadata__)
  void skip_value() {
    ws();
    if (p >= lim) {
      set_err("truncated value");
      return;
    }
    char c = *p;
    if (c == '"') {
      parse_string();
    } else if (c == '{') {
      ++p;
      if (peek('}')) {
        ++p;
        return;
      }
      while (!fail) {
        parse_string();
        if (!eat(':')) return;
        skip_value();
        if (peek(',')) {
          ++p;
          continue;
        }
        eat('}');
        return;
      }
    } else if (c == '[') {
      ++p;
      if (peek(']')) {
        ++p;
        return;
      }
      while (!fail) {
        skip_value();
        if (peek(',')) {
          ++p;
          continue;
        }
        eat(']');
        return;
      }
    } else if (c == 't' && lim - p >= 4 && !memcmp(p, "true", 4)) {
      p += 4;
    } else if (c == 'f' && lim - p >= 5 && !memcmp(p, "false", 5)) {
      p += 5;
    } else if (c == 'n' && lim - p >= 4 && !memcmp(p, "null", 4)) {
      p += 4;
    } else {
      // number (possibly float — consume the usual charset)
      const char* q = p;
      while (p < lim && (strchr("+-.eE", *p) || (*p >= '0' && *p <= '9')))
        ++p;
      if (p == q) set_err("bad value");
    }
  }
};

struct StFile {
  void* map = nullptr;
  size_t map_len = 0;
  const uint8_t* data = nullptr;  // byte buffer start
  size_t data_len = 0;
  std::vector<Tensor> tensors;
};

size_t dtype_size(const std::string& d) {
  if (d == "F64" || d == "I64" || d == "U64") return 8;
  if (d == "F32" || d == "I32" || d == "U32") return 4;
  if (d == "F16" || d == "BF16" || d == "I16" || d == "U16") return 2;
  if (d == "F8_E4M3" || d == "F8_E5M2" || d == "I8" || d == "U8" ||
      d == "BOOL")
    return 1;
  return 0;
}

}  // namespace

extern "C" {

void st_close(StFile* f);

const char* st_last_error() { return g_error.c_str(); }

StFile* st_open(const char* path) {
  g_error.clear();
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    g_error = std::string("cannot open ") + path;
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 8) {
    g_error = "file too short for a safetensors header";
    close(fd);
    return nullptr;
  }
  size_t len = static_cast<size_t>(st.st_size);
  void* map = mmap(nullptr, len, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) {
    g_error = "mmap failed";
    return nullptr;
  }
  const uint8_t* base = static_cast<const uint8_t*>(map);
  uint64_t hlen;
  memcpy(&hlen, base, 8);  // format is little-endian; so are our targets
  if (hlen > len - 8) {
    g_error = "header length exceeds file size";
    munmap(map, len);
    return nullptr;
  }

  auto* f = new StFile;
  f->map = map;
  f->map_len = len;
  f->data = base + 8 + hlen;
  f->data_len = len - 8 - hlen;

  Parser ps{reinterpret_cast<const char*>(base + 8),
            reinterpret_cast<const char*>(base + 8 + hlen)};
  if (ps.eat('{') && !ps.peek('}')) {
    while (!ps.fail) {
      std::string name = ps.parse_string();
      if (!ps.eat(':')) break;
      if (name == "__metadata__") {
        ps.skip_value();
      } else {
        Tensor t;
        t.name = std::move(name);
        if (!ps.eat('{')) break;
        while (!ps.fail) {
          std::string key = ps.parse_string();
          if (!ps.eat(':')) break;
          if (key == "dtype") {
            t.dtype = ps.parse_string();
          } else if (key == "shape") {
            if (!ps.eat('[')) break;
            if (ps.peek(']')) {
              ++ps.p;
            } else {
              while (!ps.fail) {
                t.shape.push_back(ps.parse_int());
                if (ps.peek(',')) {
                  ++ps.p;
                  continue;
                }
                ps.eat(']');
                break;
              }
            }
          } else if (key == "data_offsets") {
            if (!ps.eat('[')) break;
            t.begin = static_cast<uint64_t>(ps.parse_int());
            if (!ps.eat(',')) break;
            t.end = static_cast<uint64_t>(ps.parse_int());
            ps.eat(']');
          } else {
            ps.skip_value();
          }
          if (ps.peek(',')) {
            ++ps.p;
            continue;
          }
          ps.eat('}');
          break;
        }
        f->tensors.push_back(std::move(t));
      }
      if (ps.peek(',')) {
        ++ps.p;
        continue;
      }
      ps.eat('}');
      break;
    }
  }
  if (ps.fail) {
    g_error = "header parse error: " + ps.err;
    st_close(f);
    return nullptr;
  }
  // validate every tensor before handing out pointers
  for (const Tensor& t : f->tensors) {
    size_t es = dtype_size(t.dtype);
    if (es == 0) {
      g_error = "inconsistent tensor entry: " + t.name;
      st_close(f);
      return nullptr;
    }
    // cap the element count at data_len / es as it is built up, so an
    // adversarial shape cannot wrap count * es around 64 bits and slip
    // past the byte-range consistency check below.  A zero dimension makes
    // the exact product 0 regardless of the other dims, so it must not
    // trip the prefix-product guard (the numpy fallback computes the exact
    // bigint product; the readers must agree on such shapes).
    const unsigned long long max_count = f->data_len / es;
    unsigned long long count = 1;
    bool bad = false;
    bool has_zero_dim = false;
    for (long long d : t.shape) {
      if (d < 0) {
        g_error = "negative dimension in tensor " + t.name;
        st_close(f);
        return nullptr;
      }
      if (d == 0) has_zero_dim = true;
    }
    if (has_zero_dim) {
      count = 0;
    } else {
      for (long long d : t.shape) {
        const unsigned long long ud = static_cast<unsigned long long>(d);
        if (count > max_count / ud) {
          bad = true;
          break;
        }
        count *= ud;
      }
    }
    if (bad || t.end < t.begin || t.end > f->data_len ||
        t.end - t.begin != count * es) {
      g_error = "inconsistent tensor entry: " + t.name;
      st_close(f);
      return nullptr;
    }
  }
  return f;
}

long st_num_tensors(StFile* f) { return static_cast<long>(f->tensors.size()); }

const char* st_name(StFile* f, long i) { return f->tensors[i].name.c_str(); }

const char* st_dtype(StFile* f, long i) { return f->tensors[i].dtype.c_str(); }

long st_ndim(StFile* f, long i) {
  return static_cast<long>(f->tensors[i].shape.size());
}

void st_shape(StFile* f, long i, long long* out) {
  const auto& s = f->tensors[i].shape;
  for (size_t d = 0; d < s.size(); ++d) out[d] = s[d];
}

const void* st_data(StFile* f, long i) {
  return f->data + f->tensors[i].begin;
}

long long st_nbytes(StFile* f, long i) {
  return static_cast<long long>(f->tensors[i].end - f->tensors[i].begin);
}

void st_close(StFile* f) {
  if (f->map) munmap(f->map, f->map_len);
  delete f;
}

}  // extern "C"
